"""SLO engine tests (telemetry.slo): window math, budgets, burn alerts.

Fake-clock unit tests for the tracker's multi-window multi-burn-rate
machinery (the fast/slow edge, watchdog re-arm, exact budget
conservation), the objective builders over existing SLIs (bucket-snapped
latency cuts, labeled gateway counter families, time-kind goodput), and
a live tiny-model server cross-check: ``LoadReport.slo`` must agree with
``GET /debug/slo`` because both classify at the identical snapped
threshold.
"""

import threading

import pytest

from dlti_tpu.config import SLOConfig, WatchdogConfig
from dlti_tpu.telemetry.slo import (
    Objective,
    SLOTracker,
    availability_objective,
    build_tracker,
    goodput_objective,
    histogram_objective,
    parse_burn_tiers,
    snap_threshold,
)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


class Counts:
    """Controllable cumulative (good, total) SLI."""

    def __init__(self):
        self.good = 0.0
        self.total = 0.0

    def __call__(self):
        return self.good, self.total

    def ok(self, n: float):
        self.good += n
        self.total += n

    def bad(self, n: float):
        self.total += n


def _tracker(counts, clock, *, target=0.9, window=100.0, tiers="4:10:2"):
    obj = Objective(name="ttft", target=target, counts_fn=counts)
    return SLOTracker([obj], window_s=window, burn_tiers=tiers, clock=clock)


# ----------------------------------------------------------------------
# Burn-rate window math
# ----------------------------------------------------------------------

def test_burn_fires_only_when_fast_and_slow_windows_agree():
    """The SRE fast/slow pairing: the short window reacts first (burst
    onset), but the tier fires only once the long window confirms the
    burn is sustained — and stops as soon as the short window goes
    quiet, even while the long window still remembers the burst."""
    clock, c = FakeClock(), Counts()
    tr = _tracker(c, clock)  # target .9, tier 4x over 10s confirmed by 2s
    tr.evaluate()  # zero point at t=0
    for t in range(1, 11):  # 10 healthy seconds, 10 req/s
        clock.t = float(t)
        c.ok(10)
        state = tr.evaluate()["ttft/all"]
    assert state["compliance"] == 1.0
    assert state["error_budget_remaining"] == 1.0
    assert not state["breaching"]

    # Burst onset: 1 s of fully-bad traffic. The 2 s window sees it
    # (burn 5x >= 4x) but the 10 s window is still mostly healthy.
    clock.t = 11.0
    c.bad(10)
    state = tr.evaluate()["ttft/all"]
    assert state["burn_rates"]["2s"] >= 4.0
    assert state["burn_rates"]["10s"] < 4.0
    assert not state["breaching"]

    # Sustained burst: by t=14 the long window crosses the factor too.
    for t in (12, 13, 14):
        clock.t = float(t)
        c.bad(10)
        state = tr.evaluate()["ttft/all"]
    assert state["burn_rates"]["10s"] >= 4.0
    assert state["burn_rates"]["2s"] >= 4.0
    assert state["breaching"]
    burns = tr.active_burns(clock.t)
    assert len(burns) == 1
    assert burns[0]["objective"] == "ttft" and burns[0]["class"] == "all"

    # Recovery: healthy traffic drains the SHORT window in 2 s, so the
    # alert clears immediately even though the long window still burns.
    for t in (15, 16, 17):
        clock.t = float(t)
        c.ok(10)
        state = tr.evaluate()["ttft/all"]
    assert state["burn_rates"]["10s"] >= 4.0   # burst still in long window
    assert state["burn_rates"]["2s"] < 4.0
    assert not state["breaching"]
    assert tr.active_burns(clock.t) == []


def test_young_tracker_never_counts_pre_history():
    """The first sample is the zero point: cumulative counters that
    predate the tracker (a server that served millions of requests
    before --slo was hot-enabled) must not count against the budget."""
    clock, c = FakeClock(100.0), Counts()
    c.good, c.total = 10.0, 1000.0   # terrible history, pre-tracker
    tr = _tracker(c, clock)
    tr.evaluate()
    clock.t = 101.0
    c.ok(10)
    state = tr.evaluate()["ttft/all"]
    assert state["total"] == 10.0    # only post-construction events
    assert state["compliance"] == 1.0
    assert not state["breaching"]


def test_counter_reset_reads_as_quiet_not_negative():
    clock, c = FakeClock(), Counts()
    tr = _tracker(c, clock)
    tr.evaluate()
    clock.t = 1.0
    c.ok(50)
    tr.evaluate()
    clock.t = 2.0
    c.good, c.total = 0.0, 0.0       # process-restart-shaped reset
    state = tr.evaluate()["ttft/all"]
    assert state["good"] == 0.0 and state["total"] == 0.0
    assert state["compliance"] == 1.0
    assert state["error_budget_remaining"] == 1.0


# ----------------------------------------------------------------------
# Budget conservation
# ----------------------------------------------------------------------

def test_error_budget_conservation_exact():
    """At every evaluation: good + bad == total, compliance == good /
    total, and budget spent == bad / ((1 - target) * total) — exactly,
    not approximately (integer event counts, exact float sums)."""
    target = 0.9
    clock, c = FakeClock(), Counts()
    tr = _tracker(c, clock, target=target, window=10_000.0,
                  tiers="4:10:2")
    tr.evaluate()
    seq = [(9, 1), (10, 0), (7, 3), (10, 0), (0, 2), (25, 5), (10, 0)]
    for i, (ok_n, bad_n) in enumerate(seq, start=1):
        clock.t = float(i)
        c.ok(ok_n)
        c.bad(bad_n)
        s = tr.evaluate()["ttft/all"]
        assert s["good"] + s["bad"] == s["total"]
        assert s["compliance"] == pytest.approx(s["good"] / s["total"])
        allowed = (1.0 - target) * s["total"]
        expect = max(0.0, 1.0 - s["bad"] / allowed)
        assert s["error_budget_remaining"] == pytest.approx(expect)
        # Cross-identity: (1 - compliance) * total is exactly the bad
        # count the budget was charged for.
        assert (1.0 - s["compliance"]) * s["total"] == \
            pytest.approx(s["bad"])
    # Totals over the run: 71 ok + 11 bad.
    s = tr.evaluate(clock.t)["ttft/all"]
    assert s["total"] == 82.0 and s["bad"] == 11.0


# ----------------------------------------------------------------------
# Watchdog slo_burn rule: edge trigger + re-arm
# ----------------------------------------------------------------------

def test_watchdog_slo_burn_edge_trigger_and_rearm():
    from dlti_tpu.telemetry import AnomalyWatchdog, TimeSeriesSampler

    clock, c = FakeClock(), Counts()
    tr = _tracker(c, clock)
    wd = AnomalyWatchdog(WatchdogConfig(enabled=True),
                         TimeSeriesSampler(interval_s=60.0),
                         slo=tr, clock=clock)

    def slo_alerts(now):
        return [a for a in wd.check_now(now) if a["rule"] == "slo_burn"]

    # The tracker is pull-driven: in production the time-series sampler
    # pulls scalars() every interval, giving the windows their sample
    # cadence. Simulate that 1 Hz pull alongside the traffic.
    tr.evaluate()
    for t in range(1, 11):
        clock.t = float(t)
        c.ok(10)
        tr.evaluate()
    assert slo_alerts(clock.t) == []           # healthy
    for t in range(11, 15):
        clock.t = float(t)
        c.bad(10)
        tr.evaluate()
    fired = slo_alerts(clock.t)
    assert len(fired) == 1                     # burst: one alert
    assert "ttft" in fired[0]["message"]
    assert fired[0]["objective"] == "ttft"
    assert fired[0]["cls"] == "all"
    assert slo_alerts(clock.t) == []           # edge-triggered: no repeat
    for t in (15, 16, 17):                     # recovery clears + re-arms
        clock.t = float(t)
        c.ok(10)
        tr.evaluate()
    assert slo_alerts(clock.t) == []
    for t in (18, 19, 20, 21):                 # second burst: fires again
        clock.t = float(t)
        c.bad(10)
        tr.evaluate()
    assert len(slo_alerts(clock.t)) == 1


# ----------------------------------------------------------------------
# Objective builders
# ----------------------------------------------------------------------

def test_snap_threshold_picks_largest_bound_at_or_below():
    buckets = (0.1, 0.25, 0.5)
    assert snap_threshold(buckets, 0.3) == 0.25
    assert snap_threshold(buckets, 0.25) == 0.25
    assert snap_threshold(buckets, 10.0) == 0.5
    assert snap_threshold(buckets, 0.05) == 0.1   # undercuts all: smallest


def test_histogram_objective_counts_at_snapped_cut():
    from dlti_tpu.telemetry.registry import Histogram

    h = Histogram("dlti_test_slo_ttft_seconds", (0.1, 0.25, 0.5),
                  help="test histogram")
    obj = histogram_objective("ttft", h, 0.3, 0.99)
    assert obj.threshold_s == 0.25            # snapped down to a bound
    for v in (0.05, 0.2, 0.25, 0.4, 9.0):
        h.observe(v)
    good, total = obj.counts_fn()
    assert (good, total) == (3.0, 5.0)        # <= 0.25 is good; 0.4, 9 bad


def test_availability_objective_sums_labeled_counter_families():
    stats = {
        'dlti_gateway_admitted_total{priority="interactive",tenant="a"}': 5,
        'dlti_gateway_admitted_total{priority="batch",tenant="a"}': 3,
        'dlti_gateway_rejected_total{priority="interactive",'
        'reason="queue_full"}': 2,
        'dlti_gateway_shed_total{priority="batch"}': 1,
        "dlti_gateway_queue_depth": 7,        # different metric: ignored
    }
    good, total = availability_objective(
        lambda: stats, 0.99).counts_fn()
    assert (good, total) == (7.0, 10.0)       # 8 admitted - 1 shed / 8 + 2
    good, total = availability_objective(
        lambda: stats, 0.99, cls="interactive").counts_fn()
    assert (good, total) == (5.0, 7.0)
    good, total = availability_objective(
        lambda: stats, 0.99, cls="batch").counts_fn()
    assert (good, total) == (2.0, 3.0)


def test_time_kind_goodput_objective_integrates_left_riemann():
    cell = {"v": 0.9}
    clock = FakeClock()
    tr = SLOTracker([goodput_objective(lambda: cell["v"],
                                       floor=0.8, target=0.9)],
                    window_s=1000.0, burn_tiers="4:10:2", clock=clock)
    for t in range(0, 9):                     # value >= floor for 8 s
        clock.t = float(t)
        tr.evaluate()
    cell["v"] = 0.5                           # dips below the floor
    clock.t = 9.0
    tr.evaluate()   # interval (8,9] judged by the 0.9 that held at t=8
    clock.t = 10.0
    s = tr.evaluate()["goodput/all"]          # (9,10] judged by the 0.5
    assert s["total"] == pytest.approx(10.0)
    assert s["good"] == pytest.approx(9.0)
    assert s["compliance"] == pytest.approx(0.9)


# ----------------------------------------------------------------------
# Validation + config gating
# ----------------------------------------------------------------------

def test_objective_and_tier_validation():
    with pytest.raises(ValueError):           # target 1.0: zero budget
        Objective(name="x", target=1.0, counts_fn=lambda: (0, 0))
    with pytest.raises(ValueError):
        Objective(name="x", target=0.0, counts_fn=lambda: (0, 0))
    with pytest.raises(ValueError):           # events kind needs counts_fn
        Objective(name="x", target=0.9)
    with pytest.raises(ValueError):           # short must be < long
        parse_burn_tiers("4:10:10")
    with pytest.raises(ValueError):
        parse_burn_tiers("4:10")
    with pytest.raises(ValueError):
        parse_burn_tiers("0:10:2")
    assert parse_burn_tiers(" 14:60:5 , 6:300:30 ") == (
        (14.0, 60.0, 5.0), (6.0, 300.0, 30.0))


def test_build_tracker_gating():
    from dlti_tpu.telemetry import RequestTelemetry, SpanTracer

    assert build_tracker(SLOConfig(enabled=False)) is None
    # Enabled but nothing resolves to an objective: no dead engine.
    assert build_tracker(SLOConfig(enabled=True)) is None
    tel = RequestTelemetry(tracer=SpanTracer(enabled=False))
    tr = build_tracker(SLOConfig(enabled=True, ttft_threshold_s=0.25),
                       telemetry=tel)
    assert tr is not None
    assert [o.key for o in tr.objectives] == ["ttft/all"]
    # Availability needs a stats_fn AND a nonzero target.
    tr = build_tracker(
        SLOConfig(enabled=True, availability_target=0.999),
        stats_fn=lambda: {}, classes=("interactive", "batch"))
    assert [o.key for o in tr.objectives] == [
        "availability/all", "availability/interactive",
        "availability/batch"]


def test_scalars_and_to_dict_shapes():
    clock, c = FakeClock(), Counts()
    tr = _tracker(c, clock)
    c.ok(4)
    clock.t = 1.0
    sc = tr.scalars(clock.t)
    assert sc["slo_objectives"] == 1
    assert sc["slo_breaching"] == 0
    assert sc["slo_compliance"] == {"ttft/all": 1.0}
    assert 0.0 <= sc["slo_min_budget_remaining"] <= 1.0
    d = tr.to_dict(clock.t)
    assert d["num_objectives"] == 1 and d["breaching"] == []
    assert d["burn_tiers"] == [
        {"factor": 4.0, "long_s": 10.0, "short_s": 2.0}]
    assert d["objectives"]["ttft/all"]["kind"] == "events"
    # Empty tracker still produces a well-formed scalar dict.
    assert SLOTracker(clock=clock).scalars(0.0) == {"slo_objectives": 0}


def test_tracker_thread_safety_smoke():
    """Concurrent pulls (sampler / watchdog / HTTP all pull the same
    tracker) must not corrupt state or raise."""
    clock, c = FakeClock(), Counts()
    tr = _tracker(c, clock, window=50.0)
    stop = threading.Event()
    errors = []

    def pull():
        try:
            while not stop.is_set():
                tr.scalars()
                tr.active_burns()
                tr.to_dict()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=pull) for _ in range(3)]
    for t in threads:
        t.start()
    for i in range(200):
        clock.t += 0.01
        c.ok(1)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors
