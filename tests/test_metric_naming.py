"""Static guard: every metric the package registers follows the
``dlti_`` prefix + snake_case convention.

The /metrics names are a scrape contract (test_bench_contract pins the
known sets); this guard closes the gap for *new* names — a metric added
anywhere in the package that breaks the convention fails here before it
can silently break external dashboards. It walks a fully-assembled
serving registry (engine stats + lifecycle histograms + gateway +
heartbeat + watchdog/flight counters + the trace eviction counter) after
importing the trainer and server modules, plus every module-level metric
object the training side owns (checkpoint store, prefetch, watchdog,
flight recorder, elastic supervisor).
"""

import re

import pytest

# Importing these modules materializes every module-level metric object
# in the package (checkpoint store counters, watchdog/flight counters).
import dlti_tpu.serving.server as server_mod
import dlti_tpu.training.trainer  # noqa: F401

NAME_RE = re.compile(r"^dlti_[a-z0-9]+(_[a-z0-9]+)*$")


def _assert_convention(names, where):
    bad = [n for n in names if not NAME_RE.fullmatch(n)]
    assert not bad, (
        f"metric names breaking the dlti_ + snake_case convention in "
        f"{where}: {bad} — the /metrics exposition is a scrape contract; "
        f"rename before shipping")


def test_pinned_name_tuples_follow_convention():
    from dlti_tpu.checkpoint import CKPT_METRIC_NAMES
    from dlti_tpu.data.prefetch import PREFETCH_METRIC_NAMES
    from dlti_tpu.serving.adapters import ADAPTER_METRIC_NAMES
    from dlti_tpu.serving.deploy import DEPLOY_METRIC_NAMES
    from dlti_tpu.serving.disagg import (
        KV_HANDOFF_METRIC_NAMES, POOL_METRIC_NAMES,
    )
    from dlti_tpu.serving.engine import SPEC_METRIC_NAMES
    from dlti_tpu.serving.fleet import FLEET_METRIC_NAMES
    from dlti_tpu.serving.gateway import GATEWAY_METRIC_NAMES
    from dlti_tpu.serving.lifecycle import LIFECYCLE_METRIC_NAMES
    from dlti_tpu.serving.prefix_cache import PREFIX_CACHE_METRIC_NAMES
    from dlti_tpu.serving.wire import WIRE_METRIC_NAMES
    from dlti_tpu.telemetry import (
        FLIGHT_METRIC_NAMES, LEDGER_METRIC_NAMES,
        REQUEST_PHASE_METRIC_NAMES, SLO_METRIC_NAMES,
        WATCHDOG_METRIC_NAMES,
    )
    from dlti_tpu.telemetry.distributed_trace import TRACE_METRIC_NAMES
    from dlti_tpu.telemetry.heartbeat import HEARTBEAT_METRIC_NAMES
    from dlti_tpu.telemetry.memledger import MEMLEDGER_METRIC_NAMES
    from dlti_tpu.training.elastic import ELASTIC_METRIC_NAMES
    from dlti_tpu.training.sentinel import (
        SDC_METRIC_NAMES, SENTINEL_METRIC_NAMES,
    )
    from dlti_tpu.utils.durable_io import DISK_METRIC_NAMES

    for tup, where in ((CKPT_METRIC_NAMES, "checkpoint"),
                       (DISK_METRIC_NAMES, "durable_io"),
                       (PREFETCH_METRIC_NAMES, "prefetch"),
                       (GATEWAY_METRIC_NAMES, "gateway"),
                       (PREFIX_CACHE_METRIC_NAMES, "prefix_cache"),
                       (WATCHDOG_METRIC_NAMES, "watchdog"),
                       (FLIGHT_METRIC_NAMES, "flightrecorder"),
                       (ELASTIC_METRIC_NAMES, "elastic"),
                       (SENTINEL_METRIC_NAMES, "sentinel"),
                       (SDC_METRIC_NAMES, "sdc"),
                       (LEDGER_METRIC_NAMES, "ledger"),
                       (REQUEST_PHASE_METRIC_NAMES, "request_phase"),
                       (MEMLEDGER_METRIC_NAMES, "memledger"),
                       (SLO_METRIC_NAMES, "slo"),
                       (HEARTBEAT_METRIC_NAMES, "heartbeat"),
                       (POOL_METRIC_NAMES, "disagg-pools"),
                       (KV_HANDOFF_METRIC_NAMES, "kv-handoff"),
                       (ADAPTER_METRIC_NAMES, "adapters"),
                       (DEPLOY_METRIC_NAMES, "deploy"),
                       (LIFECYCLE_METRIC_NAMES, "lifecycle"),
                       (WIRE_METRIC_NAMES, "wire"),
                       (FLEET_METRIC_NAMES, "fleet"),
                       (SPEC_METRIC_NAMES, "spec-decode"),
                       (TRACE_METRIC_NAMES, "distributed-trace")):
        _assert_convention(tup, where)


def test_module_level_metric_objects_follow_convention():
    from dlti_tpu.checkpoint import store
    from dlti_tpu.serving import adapters, deploy, fleet, lifecycle, wire
    from dlti_tpu.telemetry import (
        distributed_trace, flightrecorder, ledger, memledger, slo, watchdog,
    )
    from dlti_tpu.training import elastic, sentinel
    from dlti_tpu.utils import durable_io

    objs = (lifecycle.quarantines_total, lifecycle.reinstates_total,
            lifecycle.flaps_total, lifecycle.migrations_total,
            lifecycle.migration_fallbacks_total,
            lifecycle.replica_state_gauge,
            wire.frames_total, wire.wire_bytes_total,
            fleet.workers_alive_gauge, fleet.respawns_total,
            adapters.loads_total, adapters.evictions_total,
            adapters.pool_hits_total, adapters.pool_misses_total,
            adapters.pool_slots_gauge, adapters.pool_bytes_gauge,
            deploy.candidates_total, deploy.canaries_total,
            deploy.promotions_total, deploy.rollbacks_total,
            deploy.rejected_total, deploy.incumbent_step_gauge,
            store.save_seconds, store.restore_seconds, store.corrupt_skipped,
            store.save_retries, store.last_verified_step,
            watchdog.alerts_total, flightrecorder.dumps_total,
            distributed_trace.federated_spans_total,
            distributed_trace.unparented_spans_total,
            distributed_trace.clock_offset_gauge,
            elastic.restarts_total, elastic.generation_gauge,
            elastic.world_size_gauge,
            sentinel.anomalies_total, sentinel.skipped_updates_total,
            sentinel.rollbacks_total, sentinel.quarantined_windows_total,
            sentinel.sdc_probes_total, sentinel.sdc_mismatches_total,
            ledger.goodput_fraction_gauge, ledger.goodput_seconds_total,
            ledger.goodput_mfu_gauge, ledger.phase_seconds_total,
            ledger.phase_requests_total,
            memledger.hbm_bytes_gauge, memledger.hbm_peak_gauge,
            memledger.hbm_headroom_gauge, memledger.hbm_untracked_gauge,
            slo.compliance_gauge, slo.budget_remaining_gauge,
            slo.burn_rate_gauge,
            durable_io.free_bytes_gauge, durable_io.write_errors_total,
            durable_io.degraded_gauge)
    _assert_convention([m.name for m in objs], "module-level metrics")


@pytest.fixture()
def full_registry():
    """A registry assembled the way a real gateway'd server assembles it,
    without paying for a real engine: a stats-shaped fake behind
    build_registry, then the gateway's counters and scalar source, the
    heartbeat gauge, and the prefetcher's metrics registered on top."""
    from dlti_tpu.config import GatewayConfig
    from dlti_tpu.serving.gateway import AdmissionGateway
    from dlti_tpu.telemetry import Heartbeat, RequestTelemetry, SpanTracer

    class FakeEngine:
        stats = {"requests": 0, "generated_tokens": 0, "prefill_tokens": 0,
                 "preemptions": 0, "decode_steps": 0, "decode_slot_steps": 0,
                 "prefix_cached_tokens": 0, "spec_proposed": 0,
                 "spec_accepted": 0, "spec_paused_rounds": 0,
                 "decode_state_uploads": 0, "decode_state_rows": 0,
                 "decode_state_clean_syncs": 0}
        telemetry = RequestTelemetry(tracer=SpanTracer(enabled=False))
        waiting: list = []
        num_active = 0
        num_free_blocks = 0

        class cfg:
            max_seqs = 4

    class FakeAsync:
        engine = FakeEngine()

    registry = server_mod.build_registry(FakeAsync())
    gw = AdmissionGateway(FakeAsync(), GatewayConfig(enabled=True), registry)
    try:
        Heartbeat(registry=registry)
        from dlti_tpu.data.prefetch import PREFETCH_METRIC_NAMES

        for name in PREFETCH_METRIC_NAMES:
            registry.gauge(name) if name.endswith("depth") \
                else registry.histogram(name)
        yield registry
    finally:
        gw.shutdown()


def test_every_registered_metric_follows_convention(full_registry):
    names = full_registry.metric_names()
    # The walk actually covered the full surface (engine scalars, request
    # histograms, gateway, heartbeat, watchdog/flight, trace eviction) —
    # an empty or partial registry would vacuously pass.
    for expected in ("dlti_requests", "dlti_request_ttft_seconds",
                     "dlti_gateway_queue_depth",
                     "dlti_gateway_admitted_total",
                     "dlti_heartbeat_last_step",
                     "dlti_watchdog_alerts_total",
                     "dlti_flight_dumps_total",
                     "dlti_trace_dropped_events",
                     "dlti_trace_federated_spans_total",
                     "dlti_trace_unparented_spans_total",
                     "dlti_trace_clock_offset_seconds",
                     "dlti_train_prefetch_queue_depth",
                     "dlti_prefix_cache_hits_total",
                     "dlti_prefix_cache_blocks",
                     "dlti_prefix_cache_hit_rate",
                     "dlti_adapter_loads_total",
                     "dlti_adapter_pool_hits_total",
                     "dlti_adapter_pool_bytes",
                     "dlti_sentinel_rollbacks_total",
                     "dlti_sdc_mismatches_total",
                     "dlti_goodput_fraction",
                     "dlti_goodput_seconds_total",
                     "dlti_request_phase_seconds_total",
                     "dlti_hbm_bytes",
                     "dlti_hbm_headroom_bytes",
                     "dlti_slo_compliance",
                     "dlti_slo_error_budget_remaining",
                     "dlti_slo_burn_rate",
                     "dlti_disk_free_bytes",
                     "dlti_disk_write_errors_total",
                     "dlti_disk_degraded",
                     "dlti_replica_lifecycle_quarantines_total",
                     "dlti_replica_state",
                     "dlti_deploy_rollbacks_total",
                     "dlti_deploy_incumbent_step",
                     "dlti_spec_proposed_total",
                     "dlti_spec_acceptance_rate",
                     "dlti_spec_draft_len",
                     "dlti_heartbeat_lag_steps"):
        assert expected in names, f"walk missed {expected}: {names}"
    _assert_convention(names, "assembled serving registry")


def test_convention_guard_actually_rejects():
    """The regex does its job: names the convention forbids fail it."""
    for bad in ("requests", "dlti_CamelCase", "dlti_", "dlti__double",
                "dlti_trailing_", "vllm_requests", "dlti_has-dash"):
        assert not NAME_RE.fullmatch(bad), bad
    for good in ("dlti_requests", "dlti_gateway_queue_depth",
                 "dlti_request_ttft_seconds", "dlti_ckpt_last_verified_step"):
        assert NAME_RE.fullmatch(good), good
