"""Training-step tests: loss semantics, grad accumulation, optimizer parity,
golden-loss regression on a tiny model."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import LoRAConfig, MODEL_PRESETS, OptimizerConfig
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.training import (
    build_optimizer,
    build_schedule,
    causal_lm_loss,
    create_train_state,
    make_train_step,
)

CFG = MODEL_PRESETS["llama_tiny"]


def make_state(rng, lora=True, opt_cfg=None):
    lora_cfg = LoRAConfig(r=4, alpha=8, dropout=0.0) if lora else LoRAConfig(enabled=False)
    model = LlamaForCausalLM(CFG, lora_cfg if lora else None)
    tx = build_optimizer(opt_cfg or OptimizerConfig(warmup_steps=2))
    state = create_train_state(rng, model, tx, (2, 32), lora_enabled=lora)
    return model, state


def test_causal_lm_loss_masking():
    """Pad tokens must not contribute; uniform logits give log(V)."""
    v = 7
    logits = jnp.zeros((1, 5, v))
    ids = jnp.array([[1, 2, 3, 4, 5]])
    mask = jnp.array([[1, 1, 1, 0, 0]])
    loss_sum, n = causal_lm_loss(logits, ids, mask)
    assert float(n) == 2.0  # positions 1,2 of the shifted targets
    np.testing.assert_allclose(float(loss_sum) / 2.0, np.log(v), rtol=1e-5)


def test_loss_decreases(rng):
    model, state = make_state(rng)
    step = jax.jit(make_train_step(model, accum_steps=2))
    batch = {
        "input_ids": jax.random.randint(rng, (2, 2, 32), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((2, 2, 32), jnp.int32),
    }
    losses = []
    for i in range(25):
        state, m = step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.01
    assert int(state.step) == 25


def test_frozen_params_unchanged(rng):
    """Only LoRA params may move; base kernels stay bit-identical.

    Two steps are needed: at init lora_b == 0 makes dL/dA zero, so lora_a
    only moves once lora_b has."""
    model, state = make_state(rng, opt_cfg=OptimizerConfig(warmup_steps=0))
    step = jax.jit(make_train_step(model, accum_steps=1))
    batch = {
        "input_ids": jax.random.randint(rng, (1, 2, 32), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((1, 2, 32), jnp.int32),
    }
    before_t, before_f = state.trainable_and_frozen()
    state2, _ = step(state, batch, rng)
    state2, _ = step(state2, batch, jax.random.fold_in(rng, 1))
    after_t, after_f = state2.trainable_and_frozen()
    for k in before_f:
        np.testing.assert_array_equal(np.asarray(before_f[k]), np.asarray(after_f[k]))
    moved = any(
        not np.array_equal(np.asarray(before_t[k]), np.asarray(after_t[k]))
        for k in before_t
    )
    assert moved, "no trainable params moved"


@pytest.mark.slow
def test_grad_accum_equals_big_batch(rng):
    """accum=4 x micro=1 must equal accum=1 x micro=4 (same tokens)."""
    model, state = make_state(rng)
    ids = jax.random.randint(rng, (4, 32), 0, CFG.vocab_size)
    mask = jnp.ones((4, 32), jnp.int32)

    step_accum = jax.jit(make_train_step(model, accum_steps=4))
    step_flat = jax.jit(make_train_step(model, accum_steps=1))

    s1, m1 = step_accum(
        state,
        {"input_ids": ids[:, None, :], "loss_mask": mask[:, None, :]},
        rng,
    )
    s2, m2 = step_flat(
        state,
        {"input_ids": ids[None, :, :], "loss_mask": mask[None, :, :]},
        rng,
    )
    np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
    t1, _ = s1.trainable_and_frozen()
    t2, _ = s2.trainable_and_frozen()
    for k in t1:
        np.testing.assert_allclose(np.asarray(t1[k]), np.asarray(t2[k]),
                                   atol=1e-5, err_msg=str(k))


def test_warmup_schedule():
    """WarmupLR parity: 0 -> lr linearly over warmup, then constant
    (configs/ds_config_zero1.json:16-23)."""
    sched = build_schedule(OptimizerConfig(learning_rate=2e-4, warmup_steps=10))
    assert float(sched(0)) == 0.0
    np.testing.assert_allclose(float(sched(5)), 1e-4, rtol=1e-5)
    np.testing.assert_allclose(float(sched(10)), 2e-4, rtol=1e-5)
    np.testing.assert_allclose(float(sched(1000)), 2e-4, rtol=1e-5)


def test_grad_clipping_bounds_update(rng):
    """Global-norm clip 1.0 parity (configs/ds_config_zero1.json:44)."""
    model, state = make_state(
        rng, opt_cfg=OptimizerConfig(learning_rate=1.0, warmup_steps=0, grad_clip=1e-6)
    )
    step = jax.jit(make_train_step(model, accum_steps=1))
    batch = {
        "input_ids": jax.random.randint(rng, (1, 2, 32), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((1, 2, 32), jnp.int32),
    }
    before, _ = state.trainable_and_frozen()
    state2, _ = step(state, batch, rng)
    after, _ = state2.trainable_and_frozen()
    # With clip 1e-6 and lr 1.0, the raw update magnitude is bounded by
    # adam's unit-scale step; just assert no explosion and finite change.
    for k in before:
        delta = np.abs(np.asarray(after[k]) - np.asarray(before[k]))
        assert np.all(np.isfinite(delta))


def test_full_finetune_all_params_move(rng):
    """lora_enabled=False => every param is trainable (13B full-FT parity,
    BASELINE.json config #4)."""
    model, state = make_state(rng, lora=False, opt_cfg=OptimizerConfig(warmup_steps=0))
    step = jax.jit(make_train_step(model, accum_steps=1))
    batch = {
        "input_ids": jax.random.randint(rng, (1, 2, 32), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((1, 2, 32), jnp.int32),
    }
    state2, _ = step(state, batch, jax.random.fold_in(rng, 0))
    t_before, f_before = state.trainable_and_frozen()
    assert not f_before  # nothing frozen
    t_after, _ = state2.trainable_and_frozen()
    moved = sum(
        not np.array_equal(np.asarray(t_before[k]), np.asarray(t_after[k]))
        for k in t_before
    )
    assert moved > len(t_before) * 0.9


def test_golden_loss_regression(rng):
    """Deterministic 10-step loss trajectory on fixed seed — catches silent
    numerics regressions (the reference records its trajectory in
    train.ipynb:334 as the analog)."""
    model, state = make_state(rng)
    step = jax.jit(make_train_step(model, accum_steps=1))
    gen = jax.random.PRNGKey(123)
    batch = {
        "input_ids": jax.random.randint(gen, (1, 4, 32), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((1, 4, 32), jnp.int32),
    }
    losses = []
    for i in range(10):
        state, m = step(state, batch, jax.random.fold_in(gen, i))
        losses.append(float(m["loss"]))
    # Loose envelope golden: starting loss ~= log(vocab) and monotone-ish fall.
    assert abs(losses[0] - np.log(CFG.vocab_size)) < 0.5
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_preemption_checkpoint_and_resume(tmp_path, rng):
    """request_stop() (the SIGTERM handler's action) checkpoints at the
    next step boundary; a fresh Trainer resumes from that step."""
    import threading

    from dlti_tpu.checkpoint import latest_step
    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig, MODEL_PRESETS, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(num_epochs=1, max_steps=50, micro_batch_size=2,
                          grad_accum_steps=1, logging_steps=100,
                          metrics_csv=str(tmp_path / "m.csv")),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_strategy="steps", save_steps=1000,
                                    save_total_limit=2, async_save=False),
    )
    trainer = Trainer(cfg)

    batch = {
        "input_ids": np.asarray(jax.random.randint(
            rng, (1, 2, 16), 0, cfg.model.vocab_size)),
        "loss_mask": np.ones((1, 2, 16), np.int32),
    }

    def batches():
        for i in range(50):
            if i == 3:
                trainer.request_stop()  # deterministic "SIGTERM" mid-run
            yield batch

    state, record = trainer.train(batches_per_epoch=batches())
    stopped_at = latest_step(cfg.checkpoint.output_dir)
    assert stopped_at is not None and 0 < stopped_at < 50

    # Fresh trainer resumes from the preemption checkpoint.
    t2 = Trainer(cfg)
    s2 = t2.init_state()
    from dlti_tpu.checkpoint import restore_train_state

    s2 = restore_train_state(cfg.checkpoint.output_dir, stopped_at, s2)
    assert int(s2.step) == stopped_at


@pytest.mark.slow
def test_chunked_ce_matches_unchunked(rng):
    """loss_chunk computes the identical loss and produces the identical
    training trajectory as the full-logits path (up to summation order),
    including the chunk-padding tail and with LoRA grads flowing."""
    model, state_a = make_state(rng)
    _, state_b = make_state(rng)
    step_full = jax.jit(make_train_step(model, accum_steps=2))
    # chunk=10 does not divide seq 32 -> exercises the padded tail.
    step_chunk = jax.jit(make_train_step(model, accum_steps=2, loss_chunk=10))
    batch = {
        "input_ids": jax.random.randint(rng, (2, 2, 32), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((2, 2, 32), jnp.int32),
    }
    for i in range(5):
        r = jax.random.fold_in(rng, i)
        state_a, ma = step_full(state_a, batch, r)
        state_b, mb = step_chunk(state_b, batch, r)
        np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]),
                                   rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(state_a.params),
                    jax.tree_util.tree_leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-4)


@pytest.mark.slow
def test_chunked_ce_matches_unchunked_tied_int8(rng):
    """head_matrix must track __call__'s head exactly for the other two
    head variants: tied embeddings (fp32 projection) and an int8-quantized
    frozen head."""
    import dataclasses

    from dlti_tpu.models.quantization import quantize_params_int8

    cfg = dataclasses.replace(CFG, tie_embeddings=True)
    lora_cfg = LoRAConfig(r=4, alpha=8, dropout=0.0)
    model = LlamaForCausalLM(cfg, lora_cfg)
    tx = build_optimizer(OptimizerConfig(warmup_steps=2))
    state = create_train_state(rng, model, tx, (2, 32), lora_enabled=True)
    state = state.replace(params=quantize_params_int8(state.params))
    batch = {
        "input_ids": jax.random.randint(rng, (1, 2, 32), 0, cfg.vocab_size),
        "loss_mask": jnp.ones((1, 2, 32), jnp.int32),
    }
    step_full = jax.jit(make_train_step(model, accum_steps=1))
    step_chunk = jax.jit(make_train_step(model, accum_steps=1, loss_chunk=8))
    _, ma = step_full(state, batch, rng)
    _, mb = step_chunk(state, batch, rng)
    np.testing.assert_allclose(float(ma["loss"]), float(mb["loss"]), rtol=2e-5)


@pytest.mark.slow
def test_steps_per_sync_matches_per_step(tmp_path, rng):
    """TrainConfig.steps_per_sync: a scanned K-step window must produce the
    SAME trajectory as K separate calls (same data + per-step rng split),
    including the epoch-tail partial window that runs per-step."""
    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig, MODEL_PRESETS, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from dlti_tpu.training.trainer import Trainer

    def run(k):
        cfg = Config(
            model=MODEL_PRESETS["llama_tiny"],
            lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
            optimizer=OptimizerConfig(warmup_steps=1),
            parallel=ParallelConfig(),
            data=DataConfig(max_seq_len=16),
            train=TrainConfig(num_epochs=1, micro_batch_size=2,
                              grad_accum_steps=1, logging_steps=100,
                              steps_per_sync=k,
                              metrics_csv=str(tmp_path / f"m{k}.csv")),
            checkpoint=CheckpointConfig(save_strategy="no"),
        )
        # 7 batches with K=3: two full scanned windows + a 1-step tail
        # through the per-step path.
        batches = [
            {"input_ids": np.asarray(jax.random.randint(
                jax.random.fold_in(rng, i), (1, 2, 16), 0,
                cfg.model.vocab_size)),
             "loss_mask": np.ones((1, 2, 16), np.int32)}
            for i in range(7)
        ]
        trainer = Trainer(cfg)
        state, record = trainer.train(batches_per_epoch=batches,
                                      state=trainer.init_state(
                                          jax.random.fold_in(rng, 99)))
        return state, record

    s1, r1 = run(1)
    s3, r3 = run(3)
    assert int(s1.step) == int(s3.step) == 7
    np.testing.assert_allclose(r1.final_loss, r3.final_loss, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s3.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


@pytest.mark.slow
def test_steps_per_sync_max_steps_cap(tmp_path, rng):
    """A window never overshoots max_steps: the last window shrinks to the
    remaining step budget (and runs per-step, shape-stable)."""
    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig, MODEL_PRESETS, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(num_epochs=1, max_steps=5, micro_batch_size=2,
                          grad_accum_steps=1, logging_steps=100,
                          steps_per_sync=3,
                          metrics_csv=str(tmp_path / "m.csv")),
        checkpoint=CheckpointConfig(save_strategy="no"),
    )
    batch = {"input_ids": np.zeros((1, 2, 16), np.int32) + 5,
             "loss_mask": np.ones((1, 2, 16), np.int32)}
    trainer = Trainer(cfg)
    state, record = trainer.train(batches_per_epoch=[batch] * 20)
    assert int(state.step) == 5


@pytest.mark.slow
def test_steps_per_sync_sharded_zero3(tmp_path, rng):
    """steps_per_sync composes with the sharded (ZeRO-3 FSDP) step: the
    scanned window traces the jitted sharded step inline, keeping its
    sharding constraints; trajectory matches the per-step sharded run."""
    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig, MODEL_PRESETS, OptimizerConfig,
                                 ParallelConfig, TrainConfig, ZeROStage)
    from dlti_tpu.training.trainer import Trainer

    if jax.device_count() < 4:
        pytest.skip("needs the 4+-device CPU mesh")

    def run(k):
        cfg = Config(
            model=MODEL_PRESETS["llama_tiny"],
            lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
            optimizer=OptimizerConfig(warmup_steps=1),
            parallel=ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=4),
            data=DataConfig(max_seq_len=16),
            train=TrainConfig(num_epochs=1, micro_batch_size=4,
                              grad_accum_steps=1, logging_steps=100,
                              steps_per_sync=k,
                              metrics_csv=str(tmp_path / f"ms{k}.csv")),
            checkpoint=CheckpointConfig(save_strategy="no"),
        )
        batches = [
            {"input_ids": np.asarray(jax.random.randint(
                jax.random.fold_in(rng, 100 + i), (1, 4, 16), 0,
                cfg.model.vocab_size)),
             "loss_mask": np.ones((1, 4, 16), np.int32)}
            for i in range(4)
        ]
        trainer = Trainer(cfg)
        state, record = trainer.train(batches_per_epoch=batches,
                                      state=trainer.init_state(
                                          jax.random.fold_in(rng, 99)))
        return state, record

    s1, r1 = run(1)
    s2, r2 = run(2)
    assert int(jax.device_get(s1.step)) == int(jax.device_get(s2.step)) == 4
    np.testing.assert_allclose(r1.final_loss, r2.final_loss, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


@pytest.mark.slow
def test_steps_per_sync_ragged_tail_batch(tmp_path, rng):
    """A custom batches_per_epoch iterable whose final batch has a
    different shape (drop_last=False pattern) must not crash the window
    stack: the pending window drains per-step and the odd batch runs
    alone — same outcome the per-step jit gives via recompile."""
    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig, MODEL_PRESETS, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, logging_steps=100,
                          steps_per_sync=2,
                          metrics_csv=str(tmp_path / "mr.csv")),
        checkpoint=CheckpointConfig(save_strategy="no"),
    )

    def make(bs):
        return {"input_ids": np.zeros((1, bs, 16), np.int32) + 3,
                "loss_mask": np.ones((1, bs, 16), np.int32)}

    batches = [make(2), make(2), make(2), make(1)]  # ragged tail
    trainer = Trainer(cfg)
    state, record = trainer.train(batches_per_epoch=batches)
    assert int(state.step) == 4


def test_steps_per_sync_preemption_drops_pending_window(tmp_path, rng):
    """request_stop() while a window is filling: the queued (unrun)
    batches are dropped, the checkpoint lands at the last executed step,
    and resume replays the dropped batches (global_step never counted
    them)."""
    from dlti_tpu.checkpoint import latest_step
    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig, MODEL_PRESETS, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(num_epochs=1, max_steps=40, micro_batch_size=2,
                          grad_accum_steps=1, logging_steps=100,
                          steps_per_sync=4,
                          metrics_csv=str(tmp_path / "mp.csv")),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_strategy="steps", save_steps=1000,
                                    save_total_limit=2, async_save=False),
    )
    trainer = Trainer(cfg)
    batch = {"input_ids": np.zeros((1, 2, 16), np.int32) + 7,
             "loss_mask": np.ones((1, 2, 16), np.int32)}

    def batches():
        for i in range(40):
            if i == 5:  # mid-window: one full window (4) has run, 1 queued
                trainer.request_stop()
            yield batch

    state, record = trainer.train(batches_per_epoch=batches())
    stopped_at = latest_step(cfg.checkpoint.output_dir)
    # One full window executed (4 steps); the partially-filled second
    # window was dropped, so the preemption checkpoint is at step 4.
    assert stopped_at == 4
    assert int(state.step) == 4


@pytest.mark.slow
def test_steps_per_sync_full_finetune(tmp_path, rng):
    """Full fine-tune (bf16 params, no LoRA) under steps_per_sync: Adam
    moments must be fp32 from init, or the first update's fp32 grads
    morph the state dtype and the scan carry fails to typecheck
    (regression: caught live by a 300M --lora-r 0 --steps-per-sync run).

    The preset must actually carry bf16 params (llama_tiny is fp32, whose
    moments are fp32 regardless) or this test guards nothing."""
    import dataclasses

    from dlti_tpu.config import (CheckpointConfig, Config, DataConfig,
                                 LoRAConfig, MODEL_PRESETS, OptimizerConfig,
                                 ParallelConfig, TrainConfig)
    from dlti_tpu.training.trainer import Trainer

    bf16_tiny = dataclasses.replace(MODEL_PRESETS["llama_tiny"],
                                    dtype="bfloat16", param_dtype="bfloat16")

    def run(k):
        cfg = Config(
            model=bf16_tiny,
            lora=LoRAConfig(enabled=False),
            optimizer=OptimizerConfig(warmup_steps=1),
            parallel=ParallelConfig(),
            data=DataConfig(max_seq_len=16),
            train=TrainConfig(num_epochs=1, micro_batch_size=2,
                              grad_accum_steps=1, logging_steps=100,
                              steps_per_sync=k,
                              metrics_csv=str(tmp_path / f"mf{k}.csv")),
            checkpoint=CheckpointConfig(save_strategy="no"),
        )
        batches = [
            {"input_ids": np.asarray(jax.random.randint(
                jax.random.fold_in(rng, 50 + i), (1, 2, 16), 0,
                cfg.model.vocab_size)),
             "loss_mask": np.ones((1, 2, 16), np.int32)}
            for i in range(4)
        ]
        trainer = Trainer(cfg)
        state, record = trainer.train(batches_per_epoch=batches,
                                      state=trainer.init_state(
                                          jax.random.fold_in(rng, 99)))
        return state, record

    s2, r2 = run(2)  # scans: would raise on a dtype-morphing carry
    s1, r1 = run(1)
    assert int(s1.step) == int(s2.step) == 4
    np.testing.assert_allclose(r1.final_loss, r2.final_loss, rtol=1e-5)
