"""Hierarchical prefix-cache tiering (HBM → host → disk).

Three layers, bottom-up:

* **Allocator edge cases** that predate tiering but were untested —
  eviction ordering under mixed refcounts, partial-block tails,
  double-release, acquire-after-evict contract violations. Pure host
  data structures, no jit: these run in the tier-1 gate.
* **TieredBlockStore units** — demote/promote round-trip byte equality,
  host→disk cascade, disk budget eviction, and the corrupt-block
  quarantine path (bit-flip and truncation both read as a miss, never an
  error, with the bytes preserved under ``_quarantine/``).
* **Engine integration** (slow: jit compiles) — outputs are
  byte-identical with tiering on vs off while the tiers absorb real
  eviction traffic, restores replace re-prefill on the measured path,
  and a corrupted disk tier degrades to misses without failing a single
  request.
"""

import glob
import os

import numpy as np
import pytest

from dlti_tpu.checkpoint.chaos import FaultyIO
from dlti_tpu.serving.block_manager import BlockManager
from dlti_tpu.serving.prefix_cache import PrefixCachingAllocator
from dlti_tpu.serving.prefix_tiers import TieredBlockStore, key_digest
from dlti_tpu.utils import durable_io


def _payload(block: int, layers: int = 2) -> dict:
    """A recognizable per-block payload (content encodes the block id)."""
    rng = np.random.default_rng(block)
    return {f"l{i:05d}": {
        "k": rng.standard_normal((4, 2, 3)).astype(np.float32),
        "v": np.full((4, 2, 3), block * 10 + i, np.float32),
    } for i in range(layers)}


def _alloc_with_store(num_blocks=8, block_size=4, **store_kw):
    bm = BlockManager(num_blocks=num_blocks, block_size=block_size)
    store = TieredBlockStore(**store_kw) if store_kw else None
    fetched = {}

    def kv_fetch(block):
        fetched[block] = _payload(block)
        return fetched[block]

    pc = PrefixCachingAllocator(bm, tier_store=store,
                                kv_fetch=kv_fetch if store else None)
    return pc, bm, store, fetched


def _register(pc, tokens):
    """Prefill-shaped registration: allocate, then retire the sequence so
    its full blocks enter the cache at refcount 0."""
    n = -(-len(tokens) // pc.block_size)
    blocks = pc.allocate(n)
    assert blocks is not None
    pc.release_sequence(tokens, blocks)
    return blocks


# ----------------------------------------------------------------------
# Allocator edge cases (previously untested, pre-tiering semantics)
# ----------------------------------------------------------------------

def test_eviction_order_mixed_refcounts():
    """Eviction is LRU over refcount-0 entries ONLY: an older but pinned
    chain survives while a younger unpinned one demotes, in its own
    registration order."""
    pc, bm, store, _ = _alloc_with_store(num_blocks=8, host_blocks=10)
    tok_a = list(range(8))          # older
    tok_b = list(range(100, 108))   # younger
    _register(pc, tok_a)
    _register(pc, tok_b)
    m, _ = pc.match_prefix(tok_a + [9])
    pc.acquire(m)  # pin A (older) — B is now the only evictable chain

    assert pc.allocate(5) is not None  # free=3: must evict both B blocks
    b_keys = PrefixCachingAllocator._chain_keys(tok_b, 4)
    assert [store.tier_of(k) for k in b_keys] == ["host", "host"]
    # Demotion preserved LRU (registration) order: b0 before b1.
    assert list(store._host.keys()) == b_keys
    # A never moved: still cached in HBM, nothing of it in the tiers.
    for k in PrefixCachingAllocator._chain_keys(tok_a, 4):
        assert store.tier_of(k) is None
    m2, n2 = pc.match_prefix(tok_a + [9])
    assert n2 == 8 and m2 == m


def test_partial_block_tail_never_cached_or_demoted():
    """The partial tail block is exclusively owned: it goes straight back
    to the pool at retirement and can never demote into a tier."""
    pc, bm, store, _ = _alloc_with_store(num_blocks=8, host_blocks=10)
    tokens = list(range(10))  # 2 full blocks + a 2-token tail
    free_before = bm.num_free
    _register(pc, tokens)
    assert pc.num_cached_blocks == 2
    assert bm.num_free == free_before - 2  # tail block freed immediately

    assert pc.allocate(7) is not None  # evict (and demote) everything
    assert store.num_host_blocks == 2
    # The tier chain for the full token list stops at the 2 full blocks.
    assert len(pc.match_tiers(tokens + [42], 0)) == 2


def test_double_release_raises_not_underflows():
    pc, _, _, _ = _alloc_with_store(num_blocks=8)
    tokens = list(range(4))
    _register(pc, tokens)
    [b] = pc.match_prefix(tokens + [5])[0]
    pc.acquire([b])
    pc.release([b])
    with pytest.raises(ValueError, match="matching acquire"):
        pc.release([b])  # refcount is 0: a second release must not go -1
    with pytest.raises(ValueError, match="not cached"):
        pc.release([b + 1])  # never-cached block id


def test_acquire_after_evict_raises_all_or_nothing():
    """A caller that allocates between match_prefix and acquire (contract
    violation) can see its matched block evicted; the acquire must fail
    loudly AND undo any refs it already took."""
    pc, _, _, _ = _alloc_with_store(num_blocks=8, host_blocks=10)
    tok_a, tok_b = list(range(4)), list(range(50, 54))
    _register(pc, tok_a)
    _register(pc, tok_b)
    [a] = pc.match_prefix(tok_a + [9])[0]
    [b] = pc.match_prefix(tok_b + [9])[0]
    assert pc.allocate(6) is not None  # evicts BOTH cached blocks
    with pytest.raises(ValueError, match="evicted between"):
        pc.acquire([a])
    # All-or-nothing: a partially-valid acquire leaves no stray refs.
    tok_c = list(range(80, 84))
    [c] = _register(pc, tok_c)[:1]
    with pytest.raises(ValueError):
        pc.acquire([c, 99])  # 99: never cached
    # c's refcount went back to 0 — still evictable, pool fully drains.
    assert pc.allocate(1) is not None


def test_restored_block_reenters_cache_pinned():
    pc, _, store, _ = _alloc_with_store(num_blocks=8, host_blocks=4)
    tokens = list(range(4))
    _register(pc, tokens)
    assert pc.allocate(7) is not None  # demote it
    [key] = pc.match_tiers(tokens + [9], 0)
    payload, tier = pc.fetch_restore(key)
    assert tier == "host" and payload is not None
    pc.release_sequence([], [])  # no-op; keeps gauges callable
    pc.register_restored(key, block=1)
    # Pinned for the admitting sequence: not evictable until released.
    assert pc.num_reclaimable == 0
    m, n = pc.match_prefix(tokens + [9])
    assert m == [1] and n == 4
    pc.release([1])
    assert pc.num_reclaimable == 1


# ----------------------------------------------------------------------
# TieredBlockStore units
# ----------------------------------------------------------------------

def test_host_round_trip_byte_equality():
    store = TieredBlockStore(host_blocks=4)
    key = ((), (1, 2, 3, 4))
    p = _payload(7)
    store.put(key, p)
    got, tier = store.fetch(key)
    assert tier == "host"
    for layer in p:
        for name in p[layer]:
            a, b = p[layer][name], got[layer][name]
            assert a.dtype == b.dtype and a.shape == b.shape
            assert a.tobytes() == b.tobytes()
    # fetch pops: a second fetch is a miss (the block went back to HBM).
    assert store.fetch(key) == (None, None)


def test_disk_round_trip_byte_equality(tmp_path):
    store = TieredBlockStore(host_blocks=0, disk_dir=str(tmp_path),
                             disk_blocks=4)
    key = ((), (9, 9, 9, 9))
    p = _payload(3)
    assert store.put(key, p) == "disk"
    got, tier = store.fetch(key)
    assert tier == "disk"
    for layer in p:
        for name in p[layer]:
            assert p[layer][name].tobytes() == got[layer][name].tobytes()
            assert p[layer][name].dtype == got[layer][name].dtype
    # Promotion removed the block dir (budgets stay meaningful).
    assert not glob.glob(os.path.join(str(tmp_path), "block-*"))


def test_host_overflow_cascades_to_disk(tmp_path):
    store = TieredBlockStore(host_blocks=1, disk_dir=str(tmp_path),
                             disk_blocks=8)
    k1, k2 = ((), (1,)), ((), (2,))
    store.put(k1, _payload(1))
    store.put(k2, _payload(2))  # k1 (LRU) cascades down
    assert store.tier_of(k1) == "disk" and store.tier_of(k2) == "host"
    got, tier = store.fetch(k1)
    assert tier == "disk" and got is not None
    assert store.stats["host_puts"] == 2 and store.stats["disk_puts"] == 1


def test_disk_budget_evicts_oldest_block_dir(tmp_path):
    store = TieredBlockStore(disk_dir=str(tmp_path), disk_blocks=2)
    keys = [((), (i,)) for i in range(3)]
    for i, k in enumerate(keys):
        store.put(k, _payload(i))
    assert store.tier_of(keys[0]) is None  # oldest fell off the edge
    assert store.stats["disk_evictions"] == 1
    assert not os.path.isdir(
        os.path.join(str(tmp_path), f"block-{key_digest(keys[0])}"))
    assert store.num_disk_blocks == 2


def test_duplicate_put_is_dropped():
    store = TieredBlockStore(host_blocks=4)
    key = ((), (5,))
    assert store.put(key, _payload(1)) == "host"
    assert store.put(key, _payload(2)) is None  # same content key
    assert store.num_host_blocks == 1


def test_put_without_tiers_drops_payload(tmp_path):
    assert TieredBlockStore().put(((), (1,)), _payload(0)) is None
    with pytest.raises(ValueError, match="disk_dir"):
        TieredBlockStore(disk_blocks=4)


# ----------------------------------------------------------------------
# Corrupt-tier robustness: quarantine, miss, never a fault
# ----------------------------------------------------------------------

def _one_disk_block(tmp_path):
    store = TieredBlockStore(disk_dir=str(tmp_path), disk_blocks=4)
    key = ((), (1, 2, 3, 4))
    store.put(key, _payload(11))
    [path] = [p for k, p in store._disk.items() if k == key]
    return store, key, path


def test_bitflipped_disk_block_is_quarantined_miss(tmp_path):
    store, key, path = _one_disk_block(tmp_path)
    victim = sorted(glob.glob(os.path.join(path, "**", "*.bin"),
                              recursive=True))[0]
    raw = bytearray(open(victim, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(raw))

    assert store.fetch(key) == (None, None)  # miss, not an exception
    assert store.stats["corrupt_dropped"] == 1
    qdirs = glob.glob(os.path.join(str(tmp_path), "_quarantine", "*"))
    assert len(qdirs) == 1 and "CheckpointCorruptError" in qdirs[0]
    assert not os.path.isdir(path)  # index and live dir both gone


def test_truncated_disk_block_is_quarantined_miss(tmp_path):
    store, key, path = _one_disk_block(tmp_path)
    victim = sorted(glob.glob(os.path.join(path, "**", "*.bin"),
                              recursive=True))[0]
    raw = open(victim, "rb").read()
    open(victim, "wb").write(raw[: max(1, len(raw) // 2)])
    assert store.fetch(key) == (None, None)
    assert store.stats["corrupt_dropped"] == 1
    assert glob.glob(os.path.join(str(tmp_path), "_quarantine", "*"))


def test_missing_manifest_is_quarantined_miss(tmp_path):
    store, key, path = _one_disk_block(tmp_path)
    os.remove(os.path.join(path, "MANIFEST.json"))
    assert store.fetch(key) == (None, None)
    assert store.stats["corrupt_dropped"] == 1


def test_allocator_counts_corruption_as_tier_miss(tmp_path):
    """fetch_restore surfaces the quarantine as a plain miss plus the
    tier_corrupt_dropped stat the /stats schema carries."""
    pc, _, store, _ = _alloc_with_store(num_blocks=8, disk_dir=str(tmp_path),
                                        disk_blocks=8)
    tokens = list(range(4))
    _register(pc, tokens)
    assert pc.allocate(7) is not None  # demote to disk
    [key] = pc.match_tiers(tokens + [9], 0)
    for f in glob.glob(os.path.join(str(tmp_path), "block-*", "**", "*.bin"),
                       recursive=True):
        open(f, "wb").write(b"garbage")
    assert pc.fetch_restore(key) == (None, None)
    assert pc.stats["tier_corrupt_dropped"] == 1


# ----------------------------------------------------------------------
# Disk-tier WRITE faults: drop, degrade, reclaim — never an error
# ----------------------------------------------------------------------

@pytest.fixture()
def _clean_io():
    durable_io.reset_for_tests()
    yield
    durable_io.reset_for_tests()


def test_disk_write_fault_drops_block_and_quarantines(tmp_path, _clean_io):
    """A torn write during demotion is a dropped block (a future cache
    miss), never an exception: nothing lands at the live block path, the
    partial staging bytes are quarantined, and the next demotion after
    the fault clears round-trips byte-identically."""
    store = TieredBlockStore(disk_dir=str(tmp_path), disk_blocks=4)
    key = ((), (1, 2, 3, 4))
    with FaultyIO.from_spec("*.bin:torn"):
        assert store.put(key, _payload(5)) is None  # dropped, no raise
    assert store.stats["disk_write_failures"] == 1
    assert store.tier_of(key) is None
    assert store.fetch(key) == (None, None)
    assert not glob.glob(os.path.join(str(tmp_path), "block-*"))
    assert glob.glob(os.path.join(str(tmp_path), "_quarantine", "*"))

    p = _payload(5)
    assert store.put(key, p) == "disk"  # fault cleared: probe lands
    got, tier = store.fetch(key)
    assert tier == "disk"
    for layer in p:
        for name in p[layer]:
            assert p[layer][name].tobytes() == got[layer][name].tobytes()


def test_disk_tier_degrades_memory_only_then_auto_recovers(tmp_path,
                                                           _clean_io):
    """``disk_fail_limit`` consecutive write failures flip the tier
    memory-only; during the cooldown demotions are skipped WITHOUT
    touching the disk; after the cooldown the next demotion probes and
    a success re-arms the tier."""
    now = [0.0]
    store = TieredBlockStore(disk_dir=str(tmp_path), disk_blocks=8,
                             disk_fail_limit=2, disk_retry_cooldown_s=10.0,
                             clock=lambda: now[0])
    keys = [((), (i,)) for i in range(5)]
    inj = FaultyIO.from_spec("*.bin:EIO")
    with inj:
        assert store.put(keys[0], _payload(0)) is None
        assert not store.disk_degraded        # one strike: still trying
        assert store.put(keys[1], _payload(1)) is None
        assert store.disk_degraded            # second strike: flipped
        fired = inj.total_fired
        assert store.put(keys[2], _payload(2)) is None
        assert inj.total_fired == fired       # skipped: disk never touched
    assert store.stats["disk_write_failures"] == 2
    assert store.stats["disk_degraded_skips"] == 1
    # Fault gone but cooldown not elapsed: still memory-only.
    assert store.put(keys[3], _payload(3)) is None
    assert store.stats["disk_degraded_skips"] == 2
    now[0] = 11.0  # cooldown expired: next demotion probes the disk
    assert store.put(keys[4], _payload(4)) == "disk"
    assert not store.disk_degraded
    assert store.fetch(keys[4])[1] == "disk"
    # Fully re-armed: subsequent demotions write through again.
    assert store.put(keys[0], _payload(0)) == "disk"


def test_disk_tier_enospc_reclaims_cold_blocks(tmp_path, _clean_io):
    """ENOSPC during a demotion triggers the store's own reclaimer: the
    coldest live blocks are quota-evicted (each is just a future cache
    hit) and the free retry lands the new block."""
    store = TieredBlockStore(disk_dir=str(tmp_path), disk_blocks=8)
    for i in range(3):
        assert store.put(((), (i,)), _payload(i)) == "disk"
    with FaultyIO.from_spec("*.bin:ENOSPC:1"):
        assert store.put(((), (9,)), _payload(9)) == "disk"
    # The LRU-coldest block was sacrificed to keep the tier writing.
    assert store.tier_of(((), (0,))) is None
    assert store.stats["disk_evictions"] >= 1
    led = durable_io.disk_ledger()["prefix_tier"]
    assert led["reclaims"] == 1 and led["reclaimed_bytes"] > 0
    got, tier = store.fetch(((), (9,)))
    assert tier == "disk" and got is not None


# ----------------------------------------------------------------------
# Engine integration (jit-heavy: slow tier, like test_prefix_cache.py)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_params():
    import jax
    import jax.numpy as jnp

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM

    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.serving import EngineConfig, InferenceEngine

    defaults = dict(max_seqs=1, block_size=8, num_blocks=7, max_model_len=40,
                    cache_dtype="float32", eos_token_id=-1,
                    enable_prefix_caching=True)
    defaults.update(kw)
    return InferenceEngine(MODEL_PRESETS["llama_tiny"], params,
                           EngineConfig(**defaults))


def _session_prompts():
    # 4 "sessions": shared 8-token block + per-session block + tail. An
    # HBM pool of 6 allocatable blocks cannot hold all of them at once.
    return [[i] * 8 + [7] * 8 + [1, 2, 3] for i in range(4)]


@pytest.mark.slow
def test_engine_tiered_outputs_byte_identical_and_prefill_saved(tmp_path,
                                                                tiny_params):
    """Acceptance: tiering on vs off is byte-identical, while the tiers
    absorb eviction traffic and restores replace re-prefill."""
    from dlti_tpu.serving import SamplingParams

    tiered = _engine(tiny_params, prefix_host_blocks=2,
                     prefix_disk_dir=str(tmp_path), prefix_disk_blocks=16)
    plain = _engine(tiny_params)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    for _ in range(2):  # round 2 revisits everything the pool evicted
        for p in _session_prompts():
            [rt] = tiered.generate([p], sp)
            [rp] = plain.generate([p], sp)
            assert rt.output_token_ids == rp.output_token_ids
    assert tiered.stats["prefix_restored_tokens"] > 0
    assert tiered.prefix_cache.stats["demotions"] > 0
    assert tiered.prefix_cache.tier_store.stats["disk_hits"] > 0
    # The headline: restores shrink prefill below the untier'd engine's.
    assert tiered.stats["prefill_tokens"] < plain.stats["prefill_tokens"]


@pytest.mark.slow
def test_engine_host_tier_only_round_trip(tiny_params):
    from dlti_tpu.serving import SamplingParams

    eng = _engine(tiny_params, prefix_host_blocks=8)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    outs = {}
    for p in _session_prompts():
        [r] = eng.generate([p], sp)
        outs[tuple(p)] = r.output_token_ids
    for p in _session_prompts():
        [r] = eng.generate([p], sp)
        assert r.output_token_ids == outs[tuple(p)]
    assert eng.prefix_cache.tier_store.stats["host_hits"] > 0
    assert eng.stats["prefix_restored_tokens"] > 0


@pytest.mark.slow
def test_engine_corrupt_disk_tier_degrades_to_miss(tmp_path, tiny_params):
    """Chaos: every on-disk block bit-flipped mid-run. Requests still
    complete with byte-identical outputs (the tier reads as cold), the
    blocks are quarantined, and the engine never faults."""
    from dlti_tpu.serving import SamplingParams

    eng = _engine(tiny_params, prefix_disk_dir=str(tmp_path),
                  prefix_disk_blocks=16)
    plain = _engine(tiny_params)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    for p in _session_prompts():
        eng.generate([p], sp)
        plain.generate([p], sp)
    assert eng.prefix_cache.stats["demotions"] > 0

    for f in glob.glob(os.path.join(str(tmp_path), "block-*", "**", "*.bin"),
                       recursive=True):
        raw = bytearray(open(f, "rb").read())
        raw[0] ^= 0xFF
        open(f, "wb").write(bytes(raw))

    for p in _session_prompts():
        [rt] = eng.generate([p], sp)
        [rp] = plain.generate([p], sp)
        assert rt.output_token_ids == rp.output_token_ids  # no fault, no drift
    assert eng.prefix_cache.stats["tier_corrupt_dropped"] > 0
    assert glob.glob(os.path.join(str(tmp_path), "_quarantine", "*"))
