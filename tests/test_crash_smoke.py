"""Real-SIGKILL chaos drill against ``scripts/train.py`` (slow tier).

The honest version of what ``tests/test_crash_consistency.py`` simulates
in-process: the CLI trainer is launched as a subprocess with
``--fault-inject-step``, SIGKILLs *itself* at an exact step (or
mid-async-save), is re-run with resume, and must finish with the exact
per-step losses of an uninterrupted run — weights, data cursor, and rng
schedule all recovered through a process boundary with no Python
teardown whatsoever.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TRAIN = os.path.join(REPO, "scripts", "train.py")


def _write_corpus(path, n=160):
    rng = np.random.default_rng(5)
    with open(path, "w") as f:
        for i in range(n):
            words = " ".join(f"w{int(w)}" for w in rng.integers(0, 50, 6))
            f.write(f"sample {i}: {words}\n")


def _run(tmp_path, tag, out_dir, extra, timeout=420):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    # Tiny programs compile in well under the entry points' 5 s persistent
    # cache threshold; opt level 0 keeps each cold subprocess quick.
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    cmd = [
        sys.executable, TRAIN,
        "--preset", "baseline", "--model", "llama_tiny",
        "--tokenizer", "byte",
        "--dataset-path", str(tmp_path / "corpus.txt"),
        "--output-dir", str(out_dir),
        "--max-seq-len", "32", "--per-device-batch-size", "2",
        "--gradient-accumulation-steps", "1", "--lora-r", "2",
        "--warmup-steps", "2", "--max-steps", "6", "--save-steps", "2",
        "--logging-steps", "1000",
        "--metrics-csv", str(tmp_path / f"{tag}.csv"),
        "--step-log", str(tmp_path / f"{tag}.jsonl"),
    ] + extra
    return subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=timeout)


def _losses(tmp_path, tag):
    rows = [json.loads(line) for line in open(tmp_path / f"{tag}.jsonl")]
    return {r["step"]: r["loss"] for r in rows if r.get("type") == "step"}


@pytest.mark.parametrize("fault", ["3:kill", "4:save-kill"])
def test_sigkill_resume_matches_uninterrupted_run(tmp_path, fault):
    _write_corpus(tmp_path / "corpus.txt")

    ref = _run(tmp_path, "ref", tmp_path / "ckpt_ref", [])
    assert ref.returncode == 0, ref.stderr[-2000:]
    ref_losses = _losses(tmp_path, "ref")
    assert set(ref_losses) == {1, 2, 3, 4, 5, 6}

    out = tmp_path / f"ckpt_{fault.replace(':', '_')}"
    killed = _run(tmp_path, "killed", out, ["--fault-inject-step", fault])
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, killed.stderr[-2000:])

    resumed = _run(tmp_path, "resumed", out, [])
    assert resumed.returncode == 0, resumed.stderr[-2000:]
    got = _losses(tmp_path, "resumed")
    # The resumed run replays from the newest VERIFIED checkpoint (a
    # save-kill may leave step 4 torn — quarantined, fall back to 2);
    # every step it executes must match the uninterrupted run exactly.
    assert got, "resumed run executed no steps"
    assert max(got) == 6
    for step, loss in got.items():
        assert loss == ref_losses[step], (step, loss, ref_losses[step])
    # And the final verified checkpoint is the run's last step.
    from dlti_tpu.checkpoint import latest_verified_step

    assert latest_verified_step(str(out)) == 6
