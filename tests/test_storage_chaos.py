"""Storage fault tolerance drills: injected I/O faults against the real
trainer, flight recorder, and watchdog.

The tier-1 half of the storage-chaos story (unit contracts for the
durable writer itself live in ``tests/test_durable_io.py``; the
adapter/prefix-tier write-fault cases ride in their own suites):

* **Steplog EIO mid-epoch** — telemetry writes are drop-and-count, so an
  I/O fault on the step log costs log lines, never a training step: the
  surviving lines carry the exact float losses of a clean run.
* **ENOSPC mid-async-save** — the save is skipped (bounded retries, no
  torn staging dir left), a ``disk_pressure`` alert fires off the same
  scalars the in-trainer watchdog samples, training completes, and a
  resume lands on the last pre-fault *verified* step with a bit-identical
  replay.
* **Flight recorder under ENOSPC** — the reclaim pass rotates the oldest
  dumps and the squeezed dump still lands; a persistently dead disk is
  counted (``dump_failures``) and recorded as a ``dump_failed`` event in
  the watchdog event log, never raised.
* **Watchdog** ``disk_pressure`` **rule** — all three triggers (free
  floor, error growth, degraded class), edge-triggered per episode, fed
  both synthetically and by the real ``durable_io.scalars()``.

The slow tier runs the honest versions: ``scripts/train.py`` in a
subprocess with ``DLTI_IO_FAULT`` set in its environment (the env
activation path, no in-process injector), and a serving engine whose
prefix disk tier dies mid-run yet finishes every request byte-identical
to an untier'd engine.
"""

import errno
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from dlti_tpu.checkpoint import latest_verified_step, list_checkpoint_steps
from dlti_tpu.checkpoint.chaos import FaultyIO
from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    OptimizerConfig, ParallelConfig, TelemetryConfig, TrainConfig,
    WatchdogConfig,
)
from dlti_tpu.data import TokenBatchDataset
from dlti_tpu.telemetry import (
    AnomalyWatchdog, FlightRecorder, SpanTracer, TimeSeriesSampler,
)
from dlti_tpu.telemetry import watchdog as watchdog_mod
from dlti_tpu.telemetry.flightrecorder import list_dumps
from dlti_tpu.utils import durable_io

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = MODEL_PRESETS["llama_tiny"]


@pytest.fixture(autouse=True)
def _clean_io():
    durable_io.reset_for_tests()
    yield
    durable_io.reset_for_tests()


def _watchdog(sampler, **over):
    kw = dict(enabled=True, interval_s=0.05, hung_step_min_s=30.0)
    kw.update(over)
    return AnomalyWatchdog(WatchdogConfig(**kw), sampler,
                           tracer=SpanTracer(enabled=False),
                           clock=time.monotonic)


# ----------------------------------------------------------------------
# Watchdog: disk_pressure rule + shared event log
# ----------------------------------------------------------------------

def test_disk_pressure_rule_three_triggers_edge_per_episode():
    s = TimeSeriesSampler(capacity=32)
    state = {"free": 100e9, "err": 0.0, "deg": 0.0}
    s.add_source(lambda: {"disk_free_bytes": state["free"],
                          "disk_write_errors": state["err"],
                          "disk_degraded": state["deg"]})
    wd = _watchdog(s, disk_free_floor_bytes=int(1e9))
    s.sample_now()
    assert wd.check_now() == []  # healthy; error watermark established
    # (1) error growth: one alert per growth episode.
    state["err"] = 3.0
    s.sample_now()
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["disk_pressure"]
    s.sample_now()
    assert wd.check_now() == []  # flat since last check: re-armed quietly
    state["err"] = 5.0
    s.sample_now()
    assert [a["rule"] for a in wd.check_now()] == ["disk_pressure"]
    # (2) a degraded path class: its own trigger key, own episode.
    state["deg"] = 1.0
    s.sample_now()
    assert [a["rule"] for a in wd.check_now()] == ["disk_pressure"]
    s.sample_now()
    assert wd.check_now() == []  # same degraded episode: one alert
    state["deg"] = 0.0
    s.sample_now()
    assert wd.check_now() == []  # recovery re-arms
    # (3) free bytes under the configured floor.
    state["free"] = 0.5e9
    s.sample_now()
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["disk_pressure"]
    assert "floor" in fired[0]["message"]
    state["free"] = 50e9
    s.sample_now()
    assert wd.check_now() == []


def test_disk_pressure_fires_from_real_durable_scalars(tmp_path):
    """The rule consumes ``durable_io.scalars()`` exactly as the trainer's
    scalar source exposes them: a real injected fault must alert."""
    s = TimeSeriesSampler(capacity=8)
    s.add_source(durable_io.scalars)
    wd = _watchdog(s)
    s.sample_now()
    assert wd.check_now() == []
    with FaultyIO.from_spec("*x.jsonl:EIO"):
        durable_io.append_line(str(tmp_path / "x.jsonl"), "a",
                               path_class="steplog")
    s.sample_now()
    fired = wd.check_now()  # errors grew AND a class degraded
    assert fired and {a["rule"] for a in fired} == {"disk_pressure"}


def test_event_log_shared_with_alerts(tmp_path):
    """``log_event`` appends structured non-alert events (the flight
    recorder's ``dump_failed``) to the same JSONL file alerts go to."""
    log = tmp_path / "events.jsonl"
    watchdog_mod.set_event_log_path(str(log))
    try:
        assert watchdog_mod.log_event({"event": "dump_failed",
                                       "errno": errno.ENOSPC})
    finally:
        watchdog_mod.set_event_log_path("")
    rows = [json.loads(line) for line in open(log)]
    assert rows[-1] == {"event": "dump_failed", "errno": errno.ENOSPC}
    assert watchdog_mod.log_event({"event": "x"}) is False  # unconfigured


# ----------------------------------------------------------------------
# Flight recorder: ENOSPC reclaim-and-retry, dump_failed accounting
# ----------------------------------------------------------------------

def test_flight_dump_enospc_rotates_oldest_and_lands(tmp_path):
    frdir = str(tmp_path / "fr")
    rec = FlightRecorder(frdir, tracer=SpanTracer(), keep=4,
                         min_interval_s=0.0)
    assert rec.dump(reason="a") is not None
    assert rec.dump(reason="b") is not None
    with FaultyIO.from_spec(f"{frdir}{os.sep}*:ENOSPC:1"):
        path = rec.dump(reason="squeezed")
    # The reclaim pass sacrificed old dump(s); the squeezed one landed.
    assert path is not None and os.path.isdir(path)
    assert rec.dump_failures == 0
    assert len(list_dumps(frdir)) < 3
    assert durable_io.disk_ledger()["flight"]["reclaims"] >= 1


def test_flight_dump_persistent_enospc_counted_and_logged(tmp_path):
    log = tmp_path / "events.jsonl"
    watchdog_mod.set_event_log_path(str(log))
    frdir = str(tmp_path / "fr")
    rec = FlightRecorder(frdir, tracer=SpanTracer(), min_interval_s=0.0)
    try:
        with FaultyIO.from_spec(f"{frdir}{os.sep}*:ENOSPC"):
            assert rec.dump(reason="doomed") is None  # never raises
    finally:
        watchdog_mod.set_event_log_path("")
    assert rec.dump_failures == 1
    assert list_dumps(frdir) == []  # no torn staging dir left behind
    rows = [json.loads(line) for line in open(log)]
    [row] = [r for r in rows if r.get("event") == "dump_failed"]
    assert row["errno"] == errno.ENOSPC
    assert row["reason"] == "doomed"


# ----------------------------------------------------------------------
# Trainer drills (in-process tier-1; the subprocess/env versions below)
# ----------------------------------------------------------------------

def _dataset(n=96, seq_len=16):
    rng = np.random.default_rng(11)
    seqs = [list(map(int, rng.integers(1, 500,
                                       size=int(rng.integers(6, 12)))))
            for _ in range(n)]
    return TokenBatchDataset(sequences=seqs, seq_len=seq_len, pad_id=0,
                             micro_batch_size=2, grad_accum_steps=1,
                             shard_by_host=False, pack=False)


def _cfg(tmp_path, tag, max_steps, save_steps=1000, save_strategy="steps"):
    return Config(
        model=CFG, lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16, prefetch_depth=2),
        train=TrainConfig(num_epochs=1, max_steps=max_steps,
                          micro_batch_size=2, grad_accum_steps=1,
                          logging_steps=1000,
                          metrics_csv=str(tmp_path / f"{tag}.csv")),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_strategy=save_strategy,
                                    save_steps=save_steps,
                                    save_total_limit=3, async_save=True,
                                    save_retries=1,
                                    save_retry_backoff_s=0.01),
        telemetry=TelemetryConfig(
            step_log_path=str(tmp_path / f"{tag}.jsonl")),
    )


def _losses(tmp_path, tag):
    rows = [json.loads(line) for line in open(tmp_path / f"{tag}.jsonl")]
    return {r["step"]: r["loss"] for r in rows if r.get("type") == "step"}


def test_steplog_eio_mid_epoch_never_costs_a_step(tmp_path):
    """Telemetry criticality: EIO on the step-log disk drops lines
    (counted) and self-heals when the fault clears — and the surviving
    lines carry the EXACT losses of a clean run, proving the fault never
    touched the training math or aborted a step."""
    from dlti_tpu.training.trainer import Trainer

    Trainer(_cfg(tmp_path, "ref", max_steps=6,
                 save_strategy="no")).train(dataset=_dataset())
    ref = _losses(tmp_path, "ref")
    assert len(ref) == 6

    flt_cfg = _cfg(tmp_path, "flt", max_steps=6, save_strategy="no")
    with FaultyIO.from_spec("*flt.jsonl:EIO:4"):
        state, _ = Trainer(flt_cfg).train(dataset=_dataset())
    assert int(jax.device_get(state.step)) == 6  # training completed
    got = _losses(tmp_path, "flt")
    # Dropped: the run-meta line + steps 1-3. Healed: steps 4-6 + final.
    assert set(got) == {4, 5, 6}
    for s in (4, 5, 6):
        assert got[s] == ref[s], (s, got[s], ref[s])
    led = durable_io.disk_ledger()["steplog"]
    assert led["drops"] == 4
    assert not durable_io.is_degraded("steplog")  # first success cleared it


def test_enospc_mid_async_save_skips_alerts_and_resumes_bit_identical(
        tmp_path):
    """The PR's acceptance drill, in-process: persistent ENOSPC lands on
    step 4's async save. The save is skipped (bounded retries, no torn
    staging dir), training completes, a ``disk_pressure`` alert fires off
    the same durable scalars the in-trainer watchdog samples, and resume
    restores the last pre-fault verified step (2) with the replayed steps
    bit-identical to an uninterrupted run."""
    from dlti_tpu.training.trainer import Trainer

    Trainer(_cfg(tmp_path, "ref", max_steps=6,
                 save_strategy="no")).train(dataset=_dataset())
    ref = _losses(tmp_path, "ref")

    # A watchdog over the exact scalar source the trainer feeds its own
    # sampler — driven explicitly so the assertion is free of the
    # background thread's shutdown timing.
    s = TimeSeriesSampler(capacity=8)
    s.add_source(durable_io.scalars)
    alog = tmp_path / "alerts.jsonl"
    wd = _watchdog(s, alert_log_path=str(alog))
    s.sample_now()
    assert wd.check_now() == []  # pre-fault watermark: healthy

    flt_cfg = _cfg(tmp_path, "flt", max_steps=4, save_steps=2)
    with FaultyIO.from_spec("*.tmp-4-*:ENOSPC"):
        state, _ = Trainer(flt_cfg).train(dataset=_dataset())
    assert int(jax.device_get(state.step)) == 4  # trainer never crashed
    ckpt = str(tmp_path / "ckpt")
    assert [n for n in os.listdir(ckpt) if n.startswith(".tmp-")] == []
    assert latest_verified_step(ckpt) == 2  # step 4 skipped, step 2 whole
    led = durable_io.disk_ledger()["checkpoint"]
    assert led["errors"] > 0

    s.sample_now()
    fired = wd.check_now()
    assert fired and {a["rule"] for a in fired} == {"disk_pressure"}
    assert any(r["rule"] == "disk_pressure"
               for r in map(json.loads, open(alog)))

    rest_cfg = _cfg(tmp_path, "rest", max_steps=6, save_steps=6)
    state, _ = Trainer(rest_cfg).train(dataset=_dataset())
    assert int(jax.device_get(state.step)) == 6
    got = _losses(tmp_path, "rest")
    # Resumed from step 2 (the last pre-fault verified step): replayed
    # 3..6 with float equality against the uninterrupted run.
    assert set(got) == {3, 4, 5, 6}
    for s_ in (3, 4, 5, 6):
        assert got[s_] == ref[s_], (s_, got[s_], ref[s_])
    # The resume's successful save (step 6) cleared the degraded flag.
    assert latest_verified_step(ckpt) == 6
    assert not durable_io.is_degraded("checkpoint")


# ----------------------------------------------------------------------
# Slow drills: env-activated chaos through the real CLI; dead disk tier
# under a serving engine
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_train_cli_survives_env_injected_storage_faults(tmp_path):
    """The honest version: ``scripts/train.py`` in a subprocess with
    ``DLTI_IO_FAULT`` in its environment (the env activation path — no
    in-process injector). Step 4's save hits persistent ENOSPC and the
    first steplog lines hit EIO; the run must exit 0 with the later
    checkpoints landed and the later step lines written."""
    rng = np.random.default_rng(5)
    with open(tmp_path / "corpus.txt", "w") as f:
        for i in range(160):
            words = " ".join(f"w{int(w)}" for w in rng.integers(0, 50, 6))
            f.write(f"sample {i}: {words}\n")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
    env[durable_io.IO_FAULT_ENV] = "*.tmp-4-*:ENOSPC;*steps.jsonl:EIO:2"
    steplog = tmp_path / "steps.jsonl"
    cmd = [
        sys.executable, os.path.join(REPO, "scripts", "train.py"),
        "--preset", "baseline", "--model", "llama_tiny",
        "--tokenizer", "byte",
        "--dataset-path", str(tmp_path / "corpus.txt"),
        "--output-dir", str(tmp_path / "ckpt"),
        "--max-seq-len", "32", "--per-device-batch-size", "2",
        "--gradient-accumulation-steps", "1", "--lora-r", "2",
        "--warmup-steps", "2", "--max-steps", "6", "--save-steps", "2",
        "--logging-steps", "1000",
        "--metrics-csv", str(tmp_path / "m.csv"),
        "--step-log", str(steplog),
    ]
    r = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                       text=True, timeout=420)
    assert r.returncode == 0, (r.returncode, r.stderr[-2000:])
    ckpt = str(tmp_path / "ckpt")
    # Step 4's save was skipped; 2 and 6 landed whole and verified.
    assert list_checkpoint_steps(ckpt) == [2, 6]
    assert latest_verified_step(ckpt) == 6
    # The first 2 steplog lines were dropped; later steps + final wrote.
    rows = [json.loads(line) for line in open(steplog)]
    steps = {row["step"] for row in rows if row.get("type") == "step"}
    assert 6 in steps and len(steps) >= 2
    assert any(row.get("type") == "final" for row in rows)


@pytest.mark.slow
def test_serving_dead_disk_tier_zero_client_errors(tmp_path):
    """A prefix disk tier whose writes die mid-run: demotions degrade to
    memory-only (counted), every request still completes, and outputs
    stay byte-identical to an engine with no tiers at all."""
    import jax.numpy as jnp

    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams

    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    def _engine(**kw):
        d = dict(max_seqs=1, block_size=8, num_blocks=7, max_model_len=40,
                 cache_dtype="float32", eos_token_id=-1,
                 enable_prefix_caching=True)
        d.update(kw)
        return InferenceEngine(CFG, params, EngineConfig(**d))

    tier = str(tmp_path / "tier")
    eng = _engine(prefix_disk_dir=tier, prefix_disk_blocks=16)
    plain = _engine()
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    prompts = [[i] * 8 + [7] * 8 + [1, 2, 3] for i in range(4)]
    for p in prompts:  # warm both engines; tiers absorb real evictions
        eng.generate([p], sp)
        plain.generate([p], sp)
    assert eng.prefix_cache.stats["demotions"] > 0

    store = eng.prefix_cache.tier_store
    with FaultyIO.from_spec(f"{tier}{os.sep}*:EIO"):
        for _ in range(2):  # revisit everything with the disk dead
            for p in prompts:
                [rt] = eng.generate([p], sp)
                [rp] = plain.generate([p], sp)
                assert rt.finish_reason == "length"
                assert rt.output_token_ids == rp.output_token_ids
    assert store.stats["disk_write_failures"] >= store.disk_fail_limit
    assert store.disk_degraded  # flipped memory-only, cooldown pending
    assert store.stats["disk_degraded_skips"] > 0
    # Not one request error: the engine's error path was never taken.
    assert eng.stats["requests"] == plain.stats["requests"]


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
