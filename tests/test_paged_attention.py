"""Pallas paged decode attention vs the XLA gather reference path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.ops.attention import reference_attention
from dlti_tpu.ops.kv_cache import init_paged_cache, paged_gather
from dlti_tpu.ops.pallas.paged_attention import paged_decode_attention


def _random_paged_setup(rng_seed, batch, num_heads, kv_heads, head_dim,
                        block_size, num_blocks, max_blocks, seq_lens):
    """Build a pool + disjoint random block tables with live data."""
    rng = np.random.default_rng(rng_seed)
    k_pool = rng.standard_normal(
        (num_blocks, block_size, kv_heads, head_dim)).astype(np.float32)
    v_pool = rng.standard_normal(
        (num_blocks, block_size, kv_heads, head_dim)).astype(np.float32)
    # Disjoint physical blocks per sequence (as the allocator guarantees).
    perm = rng.permutation(num_blocks)
    tables = np.full((batch, max_blocks), -1, np.int32)
    next_free = 0
    for b in range(batch):
        need = -(-seq_lens[b] // block_size)
        tables[b, :need] = perm[next_free:next_free + need]
        next_free += need
    return jnp.asarray(k_pool), jnp.asarray(v_pool), jnp.asarray(tables)


def _reference_decode(q, k_pool, v_pool, tables, seq_lens):
    """The engine's XLA path: gather the logical window, masked attention."""
    cache = {"k": k_pool, "v": v_pool}
    ck, cv = paged_gather(cache, jnp.maximum(tables, 0))
    # Query sits at position seq_len-1; positions >= seq_len are stale.
    q_pos = (seq_lens - 1)[:, None]
    return reference_attention(q, ck, cv, causal=True, q_positions=q_pos)


@pytest.mark.parametrize("num_heads,kv_heads", [(8, 8), (8, 2), (4, 1)])
def test_matches_gather_reference(num_heads, kv_heads):
    batch, head_dim, block_size = 3, 64, 16
    seq_lens = np.array([5, 37, 16], np.int32)  # partial / multi / exact block
    max_blocks = 4
    k_pool, v_pool, tables = _random_paged_setup(
        0, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=16, max_blocks=max_blocks, seq_lens=seq_lens)
    q = jnp.asarray(np.random.default_rng(1).standard_normal(
        (batch, 1, num_heads, head_dim)).astype(np.float32))

    got = paged_decode_attention(q, k_pool, v_pool, tables,
                                 jnp.asarray(seq_lens), interpret=True)
    want = _reference_decode(q, k_pool, v_pool, tables, jnp.asarray(seq_lens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_stale_pool_rows_never_leak():
    """Poison every block not in a sequence's table with huge values."""
    batch, num_heads, kv_heads, head_dim, block_size = 2, 4, 2, 32, 8
    seq_lens = np.array([3, 9], np.int32)
    k_pool, v_pool, tables = _random_paged_setup(
        2, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=8, max_blocks=2, seq_lens=seq_lens)
    used = set(np.asarray(tables)[np.asarray(tables) >= 0].tolist())
    poison = np.asarray(k_pool).copy()
    vpoison = np.asarray(v_pool).copy()
    for blk in range(8):
        if blk not in used:
            poison[blk] = 1e9
            vpoison[blk] = 1e9
    # Also poison the *tail* of the last live block beyond seq_len.
    for b in range(batch):
        last_logical = (seq_lens[b] - 1) // block_size
        phys = int(np.asarray(tables)[b, last_logical])
        vpoison[phys, seq_lens[b] % block_size or block_size:] = 1e9

    q = jnp.asarray(np.random.default_rng(3).standard_normal(
        (batch, 1, num_heads, head_dim)).astype(np.float32))
    got = paged_decode_attention(q, jnp.asarray(poison), jnp.asarray(vpoison),
                                 tables, jnp.asarray(seq_lens), interpret=True)
    assert np.isfinite(np.asarray(got)).all()
    assert np.abs(np.asarray(got)).max() < 1e4


def test_bf16_pool_fp32_accumulation():
    batch, num_heads, kv_heads, head_dim, block_size = 2, 4, 4, 64, 16
    seq_lens = np.array([30, 17], np.int32)
    k_pool, v_pool, tables = _random_paged_setup(
        4, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=8, max_blocks=2, seq_lens=seq_lens)
    q = jnp.asarray(np.random.default_rng(5).standard_normal(
        (batch, 1, num_heads, head_dim)))
    got = paged_decode_attention(
        q.astype(jnp.bfloat16), k_pool.astype(jnp.bfloat16),
        v_pool.astype(jnp.bfloat16), tables, jnp.asarray(seq_lens),
        interpret=True)
    want = _reference_decode(q.astype(jnp.float32), k_pool, v_pool, tables,
                             jnp.asarray(seq_lens))
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want), rtol=0.05, atol=0.05)


def test_jit_and_grid_edge():
    """Jits cleanly; seq_len filling every block exactly works."""
    batch, num_heads, kv_heads, head_dim, block_size = 1, 2, 2, 32, 8
    seq_lens = np.array([16], np.int32)  # == max_blocks * block_size
    k_pool, v_pool, tables = _random_paged_setup(
        6, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=4, max_blocks=2, seq_lens=seq_lens)
    q = jnp.asarray(np.random.default_rng(7).standard_normal(
        (batch, 1, num_heads, head_dim)).astype(np.float32))
    fn = jax.jit(lambda *a: paged_decode_attention(*a, interpret=True))
    got = fn(q, k_pool, v_pool, tables, jnp.asarray(seq_lens))
    want = _reference_decode(q, k_pool, v_pool, tables, jnp.asarray(seq_lens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


# ----------------------------------------------------------------------
# int8 KV pools
# ----------------------------------------------------------------------

def test_int8_pool_update_gather_roundtrip():
    """paged_update quantizes per (token, kv_head); paged_gather returns
    the dequantized window within the symmetric-int8 error bound."""
    from dlti_tpu.ops.kv_cache import paged_update, slot_mapping

    nb, bs, kvh, hd = 8, 4, 2, 16
    cache = init_paged_cache(1, nb, bs, kvh, hd, "int8")[0]
    assert cache["k"].dtype == jnp.int8
    assert cache["k_scale"].shape == (nb, bs, kvh)
    rng = np.random.default_rng(0)
    k = rng.standard_normal((1, 6, kvh, hd)).astype(np.float32) * 3.0
    bt = jnp.array([[2, 5]], jnp.int32)
    pos = jnp.arange(6, dtype=jnp.int32)[None, :]
    slots = slot_mapping(bt, pos, bs, nb)
    cache = paged_update(cache, jnp.asarray(k), jnp.asarray(k), slots)
    gk, gv = paged_gather(cache, bt)
    got = np.asarray(gk[0, :6], np.float32)
    bound = np.abs(k[0]).max(axis=-1, keepdims=True) / 127 + 1e-6
    assert np.all(np.abs(got - k[0]) <= bound + np.abs(k[0]) * 0.01)
    np.testing.assert_allclose(np.asarray(gv[0, :6], np.float32), got)


def test_int8_pool_kernel_matches_dequant_reference():
    """The Pallas kernel's in-place scale folding == gather+dequant+attend."""
    batch, num_heads, kv_heads, head_dim = 3, 4, 2, 32
    block_size, num_blocks, max_blocks = 8, 16, 4
    seq_lens = np.array([5, 17, 32], np.int32)
    kf, vf, tables = _random_paged_setup(
        7, batch, num_heads, kv_heads, head_dim, block_size, num_blocks,
        max_blocks, seq_lens)
    # Quantize the pools the way paged_update stores them.
    from dlti_tpu.ops.kv_cache import _quantize_rows

    kq, ks = _quantize_rows(kf)
    vq, vs = _quantize_rows(vf)
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal(
        (batch, 1, num_heads, head_dim)).astype(np.float32))

    got = paged_decode_attention(
        q, kq, vq, tables, jnp.asarray(seq_lens),
        k_scale=ks, v_scale=vs, interpret=True)
    # Reference: dequantized pools through the gather path.
    kd = (kq.astype(jnp.float32) * ks[..., None])
    vd = (vq.astype(jnp.float32) * vs[..., None])
    want = _reference_decode(q, kd, vd, tables, jnp.asarray(seq_lens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-4)


@pytest.mark.slow
def test_int8_kv_engine_close_to_bf16(tmp_path):
    """End-to-end: an int8-KV engine's greedy outputs track the bf16-KV
    engine on a tiny model (same contract as the int8-weights test)."""
    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams

    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]

    def mk(cache_dtype):
        ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                          max_model_len=48, eos_token_id=-1,
                          cache_dtype=cache_dtype)
        return InferenceEngine(cfg, params, ec)

    prompts = [[5, 9, 3, 7, 1], [11, 2, 6]]
    sp = SamplingParams(temperature=0.0, max_tokens=12)
    want = mk("bfloat16").generate(prompts, sp)
    got = mk("int8").generate(prompts, sp)
    for g, w in zip(got, want):
        assert len(g.output_token_ids) == len(w.output_token_ids)
        # A random tiny model's greedy argmax sits on near-ties, so
        # trajectories may fork under quantization noise and never
        # re-converge; the numerics contract lives in the kernel/roundtrip
        # tests above. Here: the first (prefill-driven) token agrees, and
        # logprobs stay close over the common prefix.
        assert g.output_token_ids[0] == w.output_token_ids[0]
        for a, b, la, lb in zip(g.output_token_ids, w.output_token_ids,
                                g.output_logprobs, w.output_logprobs):
            if a != b:
                break
            np.testing.assert_allclose(la, lb, atol=0.35)
