"""Pallas paged decode attention vs the XLA gather reference path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.ops.attention import reference_attention
from dlti_tpu.ops.kv_cache import init_paged_cache, paged_gather
from dlti_tpu.ops.pallas.paged_attention import paged_decode_attention


def _random_paged_setup(rng_seed, batch, num_heads, kv_heads, head_dim,
                        block_size, num_blocks, max_blocks, seq_lens):
    """Build a pool + disjoint random block tables with live data."""
    rng = np.random.default_rng(rng_seed)
    k_pool = rng.standard_normal(
        (num_blocks, block_size, kv_heads, head_dim)).astype(np.float32)
    v_pool = rng.standard_normal(
        (num_blocks, block_size, kv_heads, head_dim)).astype(np.float32)
    # Disjoint physical blocks per sequence (as the allocator guarantees).
    perm = rng.permutation(num_blocks)
    tables = np.full((batch, max_blocks), -1, np.int32)
    next_free = 0
    for b in range(batch):
        need = -(-seq_lens[b] // block_size)
        tables[b, :need] = perm[next_free:next_free + need]
        next_free += need
    return jnp.asarray(k_pool), jnp.asarray(v_pool), jnp.asarray(tables)


def _reference_decode(q, k_pool, v_pool, tables, seq_lens):
    """The engine's XLA path: gather the logical window, masked attention."""
    cache = {"k": k_pool, "v": v_pool}
    ck, cv = paged_gather(cache, jnp.maximum(tables, 0))
    # Query sits at position seq_len-1; positions >= seq_len are stale.
    q_pos = (seq_lens - 1)[:, None]
    return reference_attention(q, ck, cv, causal=True, q_positions=q_pos)


@pytest.mark.parametrize("num_heads,kv_heads", [(8, 8), (8, 2), (4, 1)])
def test_matches_gather_reference(num_heads, kv_heads):
    batch, head_dim, block_size = 3, 64, 16
    seq_lens = np.array([5, 37, 16], np.int32)  # partial / multi / exact block
    max_blocks = 4
    k_pool, v_pool, tables = _random_paged_setup(
        0, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=16, max_blocks=max_blocks, seq_lens=seq_lens)
    q = jnp.asarray(np.random.default_rng(1).standard_normal(
        (batch, 1, num_heads, head_dim)).astype(np.float32))

    got = paged_decode_attention(q, k_pool, v_pool, tables,
                                 jnp.asarray(seq_lens), interpret=True)
    want = _reference_decode(q, k_pool, v_pool, tables, jnp.asarray(seq_lens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_stale_pool_rows_never_leak():
    """Poison every block not in a sequence's table with huge values."""
    batch, num_heads, kv_heads, head_dim, block_size = 2, 4, 2, 32, 8
    seq_lens = np.array([3, 9], np.int32)
    k_pool, v_pool, tables = _random_paged_setup(
        2, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=8, max_blocks=2, seq_lens=seq_lens)
    used = set(np.asarray(tables)[np.asarray(tables) >= 0].tolist())
    poison = np.asarray(k_pool).copy()
    vpoison = np.asarray(v_pool).copy()
    for blk in range(8):
        if blk not in used:
            poison[blk] = 1e9
            vpoison[blk] = 1e9
    # Also poison the *tail* of the last live block beyond seq_len.
    for b in range(batch):
        last_logical = (seq_lens[b] - 1) // block_size
        phys = int(np.asarray(tables)[b, last_logical])
        vpoison[phys, seq_lens[b] % block_size or block_size:] = 1e9

    q = jnp.asarray(np.random.default_rng(3).standard_normal(
        (batch, 1, num_heads, head_dim)).astype(np.float32))
    got = paged_decode_attention(q, jnp.asarray(poison), jnp.asarray(vpoison),
                                 tables, jnp.asarray(seq_lens), interpret=True)
    assert np.isfinite(np.asarray(got)).all()
    assert np.abs(np.asarray(got)).max() < 1e4


def test_bf16_pool_fp32_accumulation():
    batch, num_heads, kv_heads, head_dim, block_size = 2, 4, 4, 64, 16
    seq_lens = np.array([30, 17], np.int32)
    k_pool, v_pool, tables = _random_paged_setup(
        4, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=8, max_blocks=2, seq_lens=seq_lens)
    q = jnp.asarray(np.random.default_rng(5).standard_normal(
        (batch, 1, num_heads, head_dim)))
    got = paged_decode_attention(
        q.astype(jnp.bfloat16), k_pool.astype(jnp.bfloat16),
        v_pool.astype(jnp.bfloat16), tables, jnp.asarray(seq_lens),
        interpret=True)
    want = _reference_decode(q.astype(jnp.float32), k_pool, v_pool, tables,
                             jnp.asarray(seq_lens))
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want), rtol=0.05, atol=0.05)


def test_jit_and_grid_edge():
    """Jits cleanly; seq_len filling every block exactly works."""
    batch, num_heads, kv_heads, head_dim, block_size = 1, 2, 2, 32, 8
    seq_lens = np.array([16], np.int32)  # == max_blocks * block_size
    k_pool, v_pool, tables = _random_paged_setup(
        6, batch, num_heads, kv_heads, head_dim, block_size,
        num_blocks=4, max_blocks=2, seq_lens=seq_lens)
    q = jnp.asarray(np.random.default_rng(7).standard_normal(
        (batch, 1, num_heads, head_dim)).astype(np.float32))
    fn = jax.jit(lambda *a: paged_decode_attention(*a, interpret=True))
    got = fn(q, k_pool, v_pool, tables, jnp.asarray(seq_lens))
    want = _reference_decode(q, k_pool, v_pool, tables, jnp.asarray(seq_lens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
