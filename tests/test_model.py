"""Model unit tests: shapes, RoPE, RMSNorm, GQA, LoRA semantics, caching."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import LoRAConfig, MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM, count_params, merge_lora_params
from dlti_tpu.models.llama import RMSNorm
from dlti_tpu.ops.attention import make_causal_mask, reference_attention
from dlti_tpu.ops.rope import apply_rope, rope_frequencies

CFG = MODEL_PRESETS["llama_tiny"]


def _init(model, rng, batch=2, seq=16):
    ids = jnp.zeros((batch, seq), jnp.int32)
    return model.init(rng, ids)["params"]


def test_forward_shapes(rng):
    model = LlamaForCausalLM(CFG)
    params = _init(model, rng)
    ids = jax.random.randint(rng, (2, 16), 0, CFG.vocab_size)
    logits, cache = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, CFG.vocab_size)
    assert logits.dtype == jnp.float32
    assert cache is None


def test_rmsnorm_matches_formula(rng):
    x = jax.random.normal(rng, (2, 8, 32))
    mod = RMSNorm(eps=1e-5)
    params = mod.init(rng, x)
    out = mod.apply(params, x)
    expected = x / np.sqrt(np.mean(np.square(x), axis=-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5)


def test_rope_preserves_norm_and_relativity(rng):
    """RoPE is a rotation (norm-preserving) and q·k depends only on the
    relative position offset."""
    d, seq = 64, 32
    cos, sin = rope_frequencies(d, seq)
    x = jax.random.normal(rng, (1, seq, 1, d))
    pos = jnp.arange(seq)[None, :]
    rx = apply_rope(x, cos, sin, pos)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(rx), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )
    # Relativity: <R_m q, R_n k> == <R_{m+s} q, R_{n+s} k>
    q = jax.random.normal(jax.random.fold_in(rng, 1), (1, 1, 1, d))
    k = jax.random.normal(jax.random.fold_in(rng, 2), (1, 1, 1, d))
    def dot_at(m, n):
        rq = apply_rope(q, cos, sin, jnp.array([[m]]))
        rk = apply_rope(k, cos, sin, jnp.array([[n]]))
        return float(jnp.sum(rq * rk))
    assert abs(dot_at(3, 1) - dot_at(13, 11)) < 1e-3


def test_causal_mask_decode_offset():
    m = make_causal_mask(1, 4)
    assert m.shape == (1, 1, 1, 4)
    assert np.all(np.asarray(m) == 0.0)  # single query sees whole prefix
    m2 = np.asarray(make_causal_mask(2, 4))[0, 0]
    assert m2[0, 3] < -1e30 and m2[1, 3] == 0.0


def test_gqa_equals_mha_when_heads_repeat(rng):
    """GQA with kv repeated == full MHA with duplicated kv heads."""
    b, s, h, kv, d = 2, 8, 4, 2, 16
    q = jax.random.normal(rng, (b, s, h, d))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (b, s, kv, d))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (b, s, kv, d))
    out_gqa = reference_attention(q, k, v)
    k_full = jnp.repeat(k, h // kv, axis=2)
    v_full = jnp.repeat(v, h // kv, axis=2)
    out_mha = reference_attention(q, k_full, v_full)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha), atol=1e-5)


def test_attention_is_causal(rng):
    """Changing future tokens must not change earlier outputs."""
    model = LlamaForCausalLM(CFG)
    params = _init(model, rng)
    ids = jax.random.randint(rng, (1, 16), 0, CFG.vocab_size)
    logits1, _ = model.apply({"params": params}, ids)
    ids2 = ids.at[0, -1].set((ids[0, -1] + 1) % CFG.vocab_size)
    logits2, _ = model.apply({"params": params}, ids2)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]), atol=1e-5
    )


def test_lora_starts_as_identity(rng):
    """With B=0 init, LoRA model output == base model output."""
    base = LlamaForCausalLM(CFG)
    lora = LlamaForCausalLM(CFG, LoRAConfig(r=8, alpha=16))
    lora_params = _init(lora, rng)
    base_params = merge_lora_params(lora_params, alpha=16)
    out_lora, _ = lora.apply({"params": lora_params},
                             jnp.arange(16, dtype=jnp.int32)[None, :])
    out_base, _ = base.apply({"params": base_params},
                             jnp.arange(16, dtype=jnp.int32)[None, :])
    np.testing.assert_allclose(np.asarray(out_lora), np.asarray(out_base), atol=1e-5)


def test_lora_merge_changes_with_nonzero_b(rng):
    """After perturbing lora_b, merged base model == lora model (fold-in
    correctness, the PEFT merge_and_unload contract)."""
    lora_cfg = LoRAConfig(r=8, alpha=16)
    lora = LlamaForCausalLM(CFG, lora_cfg)
    params = _init(lora, rng)

    def bump(tree):
        if isinstance(tree, dict):
            return {k: (v * 0 + 0.02 if k == "lora_b" else bump(v)) for k, v in tree.items()}
        return tree

    params = bump(params)
    merged = merge_lora_params(params, alpha=16)
    base = LlamaForCausalLM(CFG)
    ids = jnp.arange(16, dtype=jnp.int32)[None, :]
    out_lora, _ = lora.apply({"params": params}, ids)
    out_merged, _ = base.apply({"params": merged}, ids)
    np.testing.assert_allclose(np.asarray(out_lora), np.asarray(out_merged),
                               atol=2e-4)


def test_trainable_fraction(rng):
    """LoRA trainable-param accounting: only lora_a/lora_b are trainable."""
    model = LlamaForCausalLM(CFG, LoRAConfig())
    params = _init(model, rng)
    trainable, total = count_params(params)
    # 4 target projections x 2 layers x (in*r + r*out)
    assert 0 < trainable < total
    hd = CFG.resolved_head_dim
    expected = 0
    for layer in range(CFG.num_layers):
        for name, out in [("q_proj", CFG.num_heads * hd),
                          ("k_proj", CFG.num_kv_heads * hd),
                          ("v_proj", CFG.num_kv_heads * hd),
                          ("o_proj", CFG.hidden_size)]:
            inf = CFG.hidden_size if name != "o_proj" else CFG.num_heads * hd
            expected += inf * 16 + 16 * out
    assert trainable == expected


def test_kv_cache_decode_matches_full_forward(rng):
    """Prefill+decode through the cache == one full forward (greedy logits)."""
    model = LlamaForCausalLM(CFG)
    params = _init(model, rng)
    ids = jax.random.randint(rng, (1, 12), 0, CFG.vocab_size)

    full_logits, _ = model.apply({"params": params}, ids)

    cache = model.init_cache(1, 16, dtype=jnp.float32)
    prefill, cache = model.apply(
        {"params": params}, ids[:, :8],
        positions=jnp.arange(8)[None, :], cache=cache,
    )
    np.testing.assert_allclose(np.asarray(prefill), np.asarray(full_logits[:, :8]),
                               atol=1e-4)
    for t in range(8, 12):
        step_logits, cache = model.apply(
            {"params": params}, ids[:, t:t + 1],
            positions=jnp.array([[t]]), cache=cache,
        )
        np.testing.assert_allclose(
            np.asarray(step_logits[0, 0]), np.asarray(full_logits[0, t]), atol=1e-4
        )


def test_num_params_analytic_matches_actual(rng):
    model = LlamaForCausalLM(CFG)
    params = _init(model, rng)
    actual = sum(x.size for x in jax.tree_util.tree_leaves(params))
    assert CFG.num_params() == actual


@pytest.mark.slow
def test_remat_stride_preserves_training_math(rng):
    """Selective remat (every k-th block keeps activations) is a pure
    memory/FLOPs tradeoff — two steps must produce identical losses for
    any stride."""
    import dataclasses

    from dlti_tpu.config import MODEL_PRESETS, LoRAConfig, OptimizerConfig
    from dlti_tpu.training import (
        build_optimizer, create_train_state, make_train_step,
    )

    losses = []
    for stride in (1, 2, 3):
        cfg = dataclasses.replace(MODEL_PRESETS["llama_tiny"], remat=True,
                                  remat_stride=stride)
        model = LlamaForCausalLM(cfg, LoRAConfig(r=4, alpha=8, dropout=0.0))
        tx = build_optimizer(OptimizerConfig())
        state = create_train_state(rng, model, tx, (2, 32))
        step = jax.jit(make_train_step(model, accum_steps=1))
        batch = {
            "input_ids": jax.random.randint(rng, (1, 2, 32), 0,
                                            cfg.vocab_size),
            "loss_mask": jnp.ones((1, 2, 32), jnp.int32),
        }
        for _ in range(2):
            state, m = step(state, batch, rng)
        losses.append(float(m["loss"]))
    assert losses[0] == pytest.approx(losses[1], rel=1e-6)
    assert losses[0] == pytest.approx(losses[2], rel=1e-6)


@pytest.mark.slow
def test_packed_attention_window_is_exact(rng):
    """packed_attention_window = max doc length must not change logits:
    intra-doc attention never spans further back than the doc itself, so
    the banded sweep + segment mask equals plain segment masking."""
    import dataclasses

    import numpy as np

    from conftest import make_packed_segments
    from dlti_tpu.data.pipeline import packed_positions

    base = dataclasses.replace(MODEL_PRESETS["llama_tiny"],
                               attention_impl="reference")
    segs = make_packed_segments(2, 64, n_docs=4)
    # True max document length: count run lengths of real segments only
    # (the trailing padding run, id 0, is not a document).
    segs_np = np.asarray(segs)
    max_doc = max(int(np.sum(segs_np[b] == sid))
                  for b in range(2)
                  for sid in np.unique(segs_np[b]) if sid != 0)
    ids = jax.random.randint(rng, (2, 64), 0, base.vocab_size)
    pos = jnp.asarray(packed_positions(np.asarray(segs)))

    logits = {}
    for name, window in [("plain", 0), ("banded", max_doc)]:
        cfg = dataclasses.replace(base, packed_attention_window=window)
        model = LlamaForCausalLM(cfg, None)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        out, _ = model.apply({"params": params}, ids, positions=pos,
                             segment_ids=segs, deterministic=True)
        logits[name] = np.asarray(out)
    valid = np.asarray(segs != 0)[:, :, None]
    np.testing.assert_allclose(logits["banded"] * valid,
                               logits["plain"] * valid, atol=1e-5)


def test_forward_finite_past_preset_max_seq_len():
    """Training longer than a preset's design length must extend the
    (computed) RoPE table, not hit jnp.take's NaN fill — regression for
    the r03 experiment matrix silently NaN-ing at llama_tiny seq 512 >
    max_seq_len 128."""
    cfg = MODEL_PRESETS["llama_tiny"]
    assert cfg.max_seq_len < 512
    model = LlamaForCausalLM(cfg, None)
    ids = jnp.ones((1, 512), jnp.int32) * 5
    params = model.init(jax.random.PRNGKey(0), ids, deterministic=True)["params"]
    out = model.apply({"params": params}, ids, deterministic=True)
    logits = out[0] if isinstance(out, tuple) else out
    assert bool(jnp.isfinite(logits).all())
