"""Automatic prefix caching: allocator semantics + engine integration."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dlti_tpu.config import MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams
from dlti_tpu.serving.block_manager import BlockManager
from dlti_tpu.serving.prefix_cache import PrefixCachingAllocator

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow

CFG = MODEL_PRESETS["llama_tiny"]


# ----------------------------------------------------------------------
# Allocator unit tests
# ----------------------------------------------------------------------

def test_register_then_match_full_blocks_only():
    pc = PrefixCachingAllocator(BlockManager(num_blocks=16, block_size=4))
    blocks = pc.allocate(3)
    tokens = list(range(10))  # 2 full blocks + partial
    pc.release_sequence(tokens, blocks)
    assert pc.num_cached_blocks == 2  # partial tail freed

    # Exact prefix match; capped at len-1 so prefill keeps >= 1 token.
    m, n = pc.match_prefix(list(range(10)))
    assert n == 8 and len(m) == 2
    m, n = pc.match_prefix(list(range(8)))  # 8 tokens: only 4 usable
    assert n == 4 and len(m) == 1
    m, n = pc.match_prefix([9, 9, 9, 9, 9])
    assert n == 0 and m == []


def test_chain_key_is_positional():
    """Block 2's key depends on block 1's content: a different first block
    kills the match for later identical blocks."""
    pc = PrefixCachingAllocator(BlockManager(num_blocks=16, block_size=4))
    blocks = pc.allocate(2)
    pc.release_sequence([1, 2, 3, 4, 5, 6, 7, 8], blocks)
    m, n = pc.match_prefix([9, 9, 9, 9, 5, 6, 7, 8, 0])
    assert n == 0


def test_refcount_blocks_eviction():
    bm = BlockManager(num_blocks=6, block_size=4)  # 5 allocatable
    pc = PrefixCachingAllocator(bm)
    blocks = pc.allocate(2)
    pc.release_sequence(list(range(8)), blocks)  # 2 cached, refcount 0
    m, _ = pc.match_prefix(list(range(9)))
    pc.acquire(m)  # refcount 1

    # 3 free + 0 evictable-under-reference: a request for 4 must fail.
    assert pc.allocate(4) is None
    # Drop the reference: now eviction can reclaim the 2 cached blocks.
    pc.release_sequence(list(range(8)), m)
    got = pc.allocate(4)
    assert got is not None and len(got) == 4
    assert pc.stats["evictions"] >= 1


def test_duplicate_registration_dedupes():
    bm = BlockManager(num_blocks=16, block_size=4)
    pc = PrefixCachingAllocator(bm)
    b1 = pc.allocate(1)
    b2 = pc.allocate(1)
    pc.release_sequence([1, 2, 3, 4], b1)
    free_before = bm.num_free
    pc.release_sequence([1, 2, 3, 4], b2)  # same content, other block
    assert pc.num_cached_blocks == 1
    assert bm.num_free == free_before + 1  # duplicate freed immediately


# ----------------------------------------------------------------------
# Engine integration
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    defaults = dict(max_seqs=2, block_size=8, num_blocks=32, max_model_len=64,
                    cache_dtype="float32", eos_token_id=-1,
                    enable_prefix_caching=True)
    defaults.update(kw)
    return InferenceEngine(CFG, params, EngineConfig(**defaults))


def test_engine_prefix_hit_skips_prefill_and_matches_greedy(tiny_params):
    engine = _engine(tiny_params)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7]  # crosses block bdry
    sp = SamplingParams(temperature=0.0, max_tokens=6)

    [r1] = engine.generate([prompt], sp)
    prefill_first = engine.stats["prefill_tokens"]
    assert engine.stats["prefix_cached_tokens"] == 0

    [r2] = engine.generate([prompt], sp)
    # Second run: the prompt's full block (8 tokens) came from cache.
    assert engine.stats["prefix_cached_tokens"] == 8
    assert engine.stats["prefill_tokens"] == prefill_first + (len(prompt) - 8)
    assert r2.output_token_ids == r1.output_token_ids


def test_engine_prefix_cache_correctness_vs_uncached(tiny_params):
    """Generations through cache hits equal a fresh engine's output."""
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    shared = [7, 7, 7, 7, 2, 2, 2, 2]  # exactly one block
    prompts = [shared + [i, i + 1] for i in range(1, 4)]

    cached = _engine(tiny_params)
    cached.generate([prompts[0]], sp)  # warm the cache
    got = cached.generate(prompts[1:], sp)
    assert cached.stats["prefix_cached_tokens"] > 0

    fresh = InferenceEngine(CFG, tiny_params, EngineConfig(
        max_seqs=2, block_size=8, num_blocks=32, max_model_len=64,
        cache_dtype="float32", eos_token_id=-1))
    want = fresh.generate(prompts[1:], sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids


def test_engine_eviction_under_pressure(tiny_params):
    """A tiny pool keeps serving: cached blocks are evicted as needed."""
    engine = _engine(tiny_params, num_blocks=8, max_seqs=1, max_model_len=32)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    rng = np.random.default_rng(0)
    for i in range(12):
        prompt = [int(t) for t in rng.integers(0, CFG.vocab_size, 12)]
        [r] = engine.generate([prompt], sp)
        assert len(r.output_token_ids) == 4
    assert engine.prefix_cache.stats["evictions"] > 0
    assert engine.num_active == 0
