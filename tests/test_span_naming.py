"""Static guard: every ``tracer.span/complete/instant`` call-site name in
the package is pinned here.

The goodput ledger and the critical-path attribution parse span names
("train/*" phases, "engine/*" step phases, "request/*" lifecycle,
"gateway/*" admission); flight-record readers and ``scripts/postmortem.py``
group by them too. Like ``test_metric_naming.py`` for the ``/metrics``
exposition, this walk makes instrumentation names a *contract*: adding a
span site means adding its name to the catalog (deliberate), and a rename
fails here before it silently breaks attribution parsing or saved-trace
tooling.

The walk is an AST scan, not an import: a span behind a rarely-taken
branch is still caught, and the guard costs no jax startup.
"""

import ast
import os

import pytest

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "dlti_tpu")

# The catalog. Names group as "<plane>/<phase>"; every one is emitted via
# the process-global SpanTracer (telemetry.tracer).
SPAN_NAME_CATALOG = frozenset({
    # Trainer per-step phases (also the goodput ledger's bucket sites).
    "train/batch_fetch",
    "train/host_to_device",
    "train/step_dispatch",
    "train/device_sync",
    "train/eval",
    "train/checkpoint_save",
    "train/sdc_probe",
    "train/sentinel_rollback",
    "train/prefetch",
    # Engine step phases + the prefix-tier restore charge.
    "engine/admit",
    "engine/decode_dispatch",
    "engine/decode_sync",
    "engine/adapter_load",
    "engine/kv_handoff",
    "engine/prefill_chunks",
    "engine/tier_restore",
    # Request lifecycle (telemetry.lifecycle).
    "request/submitted",
    "request/queued",
    "request/readmitted",
    "request/prefill",
    "request/decode",
    "request/preempted",
    # Admission gateway.
    "gateway/enqueued",
    "gateway/queued",
    "gateway/rejected",
    "gateway/shed",
    # Watchdog alert instants.
    "watchdog/alert",
})

_TRACER_METHODS = ("span", "complete", "instant")

# Call sites whose first argument is not a string literal, allowed ONLY
# because their name is a literal *default* elsewhere (asserted below):
# (relative path, receiver attribute) -> the default-carrying symbol.
_DYNAMIC_ALLOWED = {
    # HostPrefetcher worker span: self._tracer.span(self._span_name, ...)
    # with span_name="train/prefetch" in the constructor signature.
    os.path.join("data", "prefetch.py"),
}


def _walk_calls():
    """Yield (relpath, lineno, first_arg_node) for every
    ``<obj>.span|complete|instant(...)`` call in the package."""
    for root, _dirs, files in os.walk(PKG):
        if "__pycache__" in root:
            continue
        for name in files:
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, PKG)
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                func = node.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr in _TRACER_METHODS):
                    continue
                if not node.args:
                    continue
                yield rel, node.lineno, node.args[0]


def _collected():
    literals = {}
    dynamic = []
    for rel, lineno, arg in _walk_calls():
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            # Only slash-namespaced strings are span names; this keeps
            # unrelated `.complete(x)`-shaped methods (none today) from
            # polluting the walk if one ever appears.
            if "/" in arg.value:
                literals.setdefault(arg.value, []).append((rel, lineno))
        else:
            dynamic.append((rel, lineno))
    return literals, dynamic


def test_every_span_call_site_name_is_pinned():
    literals, dynamic = _collected()
    unknown = set(literals) - SPAN_NAME_CATALOG
    assert not unknown, (
        f"span names not in the pinned catalog: "
        f"{ {n: literals[n] for n in unknown} } — ledger/attribution and "
        f"postmortem tooling parse span names; add new ones to "
        f"SPAN_NAME_CATALOG deliberately")
    missing = SPAN_NAME_CATALOG - set(literals) - {"train/prefetch"}
    assert not missing, (
        f"catalog names with no remaining call site: {missing} — a "
        f"renamed/removed span breaks attribution parsing; update the "
        f"catalog with the rename")
    for rel, lineno in dynamic:
        assert rel in _DYNAMIC_ALLOWED, (
            f"non-literal span name at dlti_tpu/{rel}:{lineno} — span "
            f"names are a static contract; use a literal (or add an "
            f"allowlist entry with its literal default pinned)")


def test_dynamic_prefetch_span_default_is_pinned():
    """The one allowed dynamic site (HostPrefetcher) must keep its
    literal default in the constructor signature."""
    import inspect

    from dlti_tpu.data.prefetch import HostPrefetcher

    sig = inspect.signature(HostPrefetcher.__init__)
    assert sig.parameters["span_name"].default == "train/prefetch"
    assert "train/prefetch" in SPAN_NAME_CATALOG


def test_span_names_follow_plane_slash_phase_convention():
    for name in SPAN_NAME_CATALOG:
        plane, _, phase = name.partition("/")
        assert plane and phase, name
        assert plane in ("train", "engine", "request", "gateway",
                         "watchdog"), name
        assert phase == phase.lower().replace("-", "_"), name


def test_walk_actually_sees_known_sites():
    """Anti-vacuity: the AST walk finds the long-standing sites (an empty
    walk would pass the guards above trivially)."""
    literals, _ = _collected()
    for expected in ("train/step_dispatch", "engine/admit",
                     "request/queued", "gateway/enqueued",
                     "watchdog/alert", "engine/tier_restore",
                     "engine/kv_handoff"):
        assert expected in literals, f"walk missed {expected}"
    # The kv-handoff span is emitted from BOTH handoff paths — the
    # disagg prefill->decode staging injection and the fleet drain
    # migration (the distributed trace's cross-process leg); losing
    # either call site breaks per-request timeline reconstruction.
    handoff_files = {rel for rel, _ in literals["engine/kv_handoff"]}
    for rel in (os.path.join("serving", "disagg.py"),
                os.path.join("serving", "fleet.py")):
        assert rel in handoff_files, (
            f"engine/kv_handoff call site missing from {rel}: "
            f"{handoff_files}")


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
