"""Mixture-of-Experts: routing semantics, aux loss, training, and
expert-parallel equivalence."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    OptimizerConfig, ParallelConfig, TrainConfig, ZeROStage,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.models.moe import MoEMLP, collect_aux_loss
from dlti_tpu.parallel import build_mesh, make_sharded_train_step, shard_train_state
from dlti_tpu.training import build_optimizer, create_train_state, make_train_step

CFG = MODEL_PRESETS["mixtral_tiny"]


def test_moe_mlp_shapes_and_finite(rng):
    mlp = MoEMLP(CFG)
    x = jax.random.normal(rng, (2, 8, CFG.hidden_size))
    params = mlp.init(rng, x)["params"]
    assert params["w1"].shape == (4, CFG.hidden_size, CFG.intermediate_size)
    assert params["router"].shape == (CFG.hidden_size, 4)
    y = mlp.apply({"params": params}, x)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()


def test_moe_capacity_drops_overflow(rng):
    """With capacity factor ~0, every token is dropped -> output is zero."""
    cfg = dataclasses.replace(CFG, moe_capacity_factor=1e-9)
    mlp = MoEMLP(cfg)
    x = jax.random.normal(rng, (1, 8, cfg.hidden_size))
    params = mlp.init(rng, x)["params"]
    y = mlp.apply({"params": params}, x)
    # Capacity C=1 (min): at most E tokens survive per slot; most output
    # rows are exactly zero.
    zero_rows = np.sum(np.all(np.asarray(y[0]) == 0.0, axis=-1))
    assert zero_rows >= 2


def test_moe_equals_dense_expert_when_all_experts_identical(rng):
    """If every expert has identical weights, routing is irrelevant and the
    MoE output equals a single SwiGLU expert applied densely (top-k weights
    renormalize to 1)."""
    cfg = dataclasses.replace(CFG, moe_capacity_factor=8.0)  # no drops
    mlp = MoEMLP(cfg)
    x = jax.random.normal(rng, (2, 8, cfg.hidden_size))
    params = mlp.init(rng, x)["params"]
    w1 = np.array(params["w1"])
    for e in range(1, cfg.num_experts):
        w1[e] = w1[0]
    w2 = np.array(params["w2"]); w2[:] = w2[0]
    w3 = np.array(params["w3"]); w3[:] = w3[0]
    params = {**params, "w1": jnp.asarray(w1), "w2": jnp.asarray(w2),
              "w3": jnp.asarray(w3)}
    y = mlp.apply({"params": params}, x)

    h = np.asarray(x) @ w1[0]
    g = np.asarray(x) @ w3[0]
    want = (h / (1 + np.exp(-h))) * g @ w2[0]
    np.testing.assert_allclose(np.asarray(y), want, rtol=2e-4, atol=2e-4)


def test_aux_loss_sown_and_near_one_for_uniform_router(rng):
    """Fresh (near-uniform) router => load-balance loss ~ 1 (its minimum)."""
    mlp = MoEMLP(dataclasses.replace(CFG, moe_capacity_factor=8.0))
    x = jax.random.normal(rng, (4, 32, CFG.hidden_size))
    params = mlp.init(rng, x)["params"]
    _, variables = mlp.apply({"params": params}, x, mutable=["intermediates"])
    aux = collect_aux_loss(variables["intermediates"])
    assert 0.9 < float(aux) < 1.6


@pytest.mark.slow
def test_moe_model_trains_and_loss_decreases(rng):
    model = LlamaForCausalLM(CFG, None)  # full fine-tune (no LoRA)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0, learning_rate=1e-2))
    state = create_train_state(rng, model, tx, (4, 16), lora_enabled=False)
    step = jax.jit(make_train_step(model, accum_steps=1))
    batch = {
        "input_ids": jax.random.randint(rng, (1, 4, 16), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((1, 4, 16), jnp.int32),
    }
    losses = []
    for i in range(8):
        state, m = step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.5, losses


def test_moe_serving_decode_runs(rng):
    """MoE forward with a KV cache (decode path) stays functional — sow is a
    no-op when intermediates are not mutable."""
    model = LlamaForCausalLM(CFG, None)
    ids = jax.random.randint(rng, (1, 8), 0, CFG.vocab_size)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]
    cache = model.init_cache(1, 16, dtype=jnp.float32)
    logits, cache = model.apply({"params": params}, ids,
                                positions=jnp.arange(8)[None, :], cache=cache)
    assert logits.shape == (1, 8, CFG.vocab_size)
    assert np.isfinite(np.asarray(logits)).all()


@pytest.mark.slow
def test_expert_parallel_matches_single_device(rng):
    """Forward + train step over an expert=4 mesh == unsharded step."""
    cfg = Config(
        model=CFG, lora=LoRAConfig(enabled=False),
        optimizer=OptimizerConfig(warmup_steps=0),
        parallel=ParallelConfig(zero_stage=ZeROStage.NONE, data=2, expert=4),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(micro_batch_size=4, grad_accum_steps=1),
        checkpoint=CheckpointConfig(save_strategy="no"),
    )
    mesh = build_mesh(cfg.parallel)
    model = LlamaForCausalLM(CFG, None, mesh)
    tx = build_optimizer(cfg.optimizer)
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=False)
    batch = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(1), (1, 4, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((1, 4, 16), jnp.int32),
    }
    rng2 = jax.random.PRNGKey(2)

    ref_model = LlamaForCausalLM(CFG, None)
    ref_state = create_train_state(jax.random.PRNGKey(0), ref_model, tx, (4, 16),
                                   lora_enabled=False)
    ref_step = jax.jit(make_train_step(ref_model, accum_steps=1))
    _, ref_m = ref_step(ref_state, batch, rng2)

    sstate = shard_train_state(state, cfg, mesh)
    # Expert weights really are sharded over the expert axis.
    w1 = sstate.params["model"]["layers_0"]["mlp"]["w1"]
    assert "expert" in jax.tree_util.tree_leaves(
        [w1.sharding.spec])[0:1][0] or w1.sharding.spec[0] == "expert"
    sstep = make_sharded_train_step(model, sstate, cfg, mesh, accum_steps=1)
    _, sm = sstep(sstate, batch, rng2)

    np.testing.assert_allclose(float(sm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)


def test_padding_tokens_do_not_consume_capacity(rng):
    """With token_mask marking the first sequence's tail as padding, real
    tokens of the second sequence are not displaced: output equals the
    no-padding run on the same real tokens."""
    cfg = dataclasses.replace(CFG, moe_capacity_factor=1.0)
    mlp = MoEMLP(cfg)
    x = jax.random.normal(rng, (2, 16, cfg.hidden_size))
    params = mlp.init(rng, x)["params"]
    mask = jnp.ones((2, 16), jnp.int32).at[0, 4:].set(0)

    y_masked = mlp.apply({"params": params}, x, True, mask)
    # Padding rows produce exactly zero (never dispatched).
    np.testing.assert_array_equal(
        np.asarray(y_masked[0, 4:]), np.zeros((12, cfg.hidden_size), np.float32))

    # Capacity accounting ignores pads: second sequence's outputs match a
    # run where the pad rows are the only difference.
    x2 = x.at[0, 4:].set(0.0)
    y2 = mlp.apply({"params": params}, x2, True, mask)
    np.testing.assert_allclose(np.asarray(y_masked[1]), np.asarray(y2[1]),
                               rtol=1e-5, atol=1e-6)


def test_moe_pipeline_forward_matches_unpipelined(rng):
    """MoE under PP (was rejected until r04): the pipelined forward
    reproduces the unpipelined MoE logits, and the per-microbatch aux
    vector is finite and positive."""
    import dataclasses

    from dlti_tpu.parallel.pipeline import pipeline_forward, to_pipeline_params

    cfg = dataclasses.replace(CFG, dtype="float32", param_dtype="float32",
                              attention_impl="reference")
    mesh = build_mesh(ParallelConfig(pipe=2))
    model = LlamaForCausalLM(cfg, None)
    params = model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]
    ids = jax.random.randint(jax.random.fold_in(rng, 1), (2, 8), 0,
                             cfg.vocab_size)
    # Expert capacity is per-forward-batch: compare against the dense
    # forward applied per microbatch (1 row each), matching the
    # pipeline's per-microbatch dispatch exactly.
    want = jnp.concatenate([
        model.apply({"params": params}, ids[i:i + 1],
                    deterministic=True)[0]
        for i in range(2)], axis=0)
    pp = to_pipeline_params(params, cfg.num_layers)
    got, aux = pipeline_forward(pp, ids, cfg, mesh, num_microbatches=2,
                                return_aux=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)
    assert aux.shape == (2,)
    assert np.isfinite(np.asarray(aux)).all() and np.all(np.asarray(aux) > 0)


def test_moe_lora_mlp_targets_rejected(rng):
    model = LlamaForCausalLM(
        CFG, LoRAConfig(r=2, alpha=4, target_modules=("q_proj", "gate_proj")))
    with pytest.raises(NotImplementedError, match="LoRA on MLP"):
        model.init(rng, jnp.zeros((1, 8), jnp.int32))
