"""Import-order independence: every subpackage must import standalone.

Regression guard for the training<->parallel cycle: ``dlti_tpu.parallel``
imports ``training.state``, whose package re-exports ``Trainer``, which
needs the parallel layer — safe only while trainer.py imports parallel
*submodules*, not the package. A fresh interpreter per subpackage catches
any ordering that only works because another module imported first.
"""

import subprocess
import sys

import pytest

SUBPACKAGES = [
    "dlti_tpu",
    "dlti_tpu.parallel",
    "dlti_tpu.training",
    "dlti_tpu.models",
    "dlti_tpu.data",
    "dlti_tpu.serving",
    "dlti_tpu.checkpoint",
    "dlti_tpu.ops",
    "dlti_tpu.benchmarks",
    "dlti_tpu.utils",
]


@pytest.mark.parametrize("pkg", SUBPACKAGES)
def test_subpackage_imports_standalone(pkg):
    proc = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu'); "
         f"import {pkg}"],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, f"import {pkg} failed:\n{proc.stderr[-2000:]}"


def test_everything_compiles():
    """Whole-repo py_compile gate: a snapshot that does not parse can never
    ship again (round 1 shipped a half-applied edit leaving trainer.py with
    a SyntaxError at HEAD)."""
    import compileall
    import pathlib

    root = pathlib.Path(__file__).resolve().parents[1]
    targets = [root / "dlti_tpu", root / "scripts", root / "tests",
               root / "bench.py", root / "__graft_entry__.py"]
    for t in targets:
        if t.is_dir():
            ok = compileall.compile_dir(str(t), quiet=2)
        else:
            ok = compileall.compile_file(str(t), quiet=2)
        assert ok, f"python sources under {t} failed to compile"
