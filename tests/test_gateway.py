"""Admission gateway tests (tiny model, CPU, ephemeral ports).

Two layers, mirroring the subsystem's own split:

* **Scheduling-policy units** against a fake engine with controllable slot
  headroom — queue bounds, per-tenant token buckets, weighted fair
  dequeue, strict priority classes, queued-deadline shed. Deterministic:
  no live decode races the assertions.
* **Full-stack integration** over real sockets — a loadgen burst past the
  queue bound sheds 429 + Retry-After while admitted requests finish;
  SIGTERM-style drain flips /health and refuses new work while in-flight
  completes; a fault-injected replica kill fails its requests over to the
  survivor with zero client-visible errors and the retries visible in
  ``dlti_gateway_retries_total``.
"""

import http.client
import json
import threading
import time
import types

import jax
import jax.numpy as jnp
import pytest

from dlti_tpu.config import GatewayConfig, MODEL_PRESETS
from dlti_tpu.data.tokenizer import IdTokenizer
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import (
    AdmissionError, EngineConfig, InferenceEngine, ReplicatedEngine,
    SamplingParams,
)
from dlti_tpu.serving.engine import Request
from dlti_tpu.serving.gateway import AdmissionGateway
from dlti_tpu.serving.server import ServerConfig, make_server
from dlti_tpu.telemetry import MetricsRegistry, RequestTelemetry

CFG = MODEL_PRESETS["llama_tiny"]


# ----------------------------------------------------------------------
# Scheduling-policy units (fake engine: no decode, controllable headroom)
# ----------------------------------------------------------------------

class _FakeAsyncEngine:
    """AsyncEngine stand-in: records dispatch order; `room` gates it."""

    def __init__(self, room: int = 0):
        self.engine = types.SimpleNamespace(
            cfg=types.SimpleNamespace(max_seqs=room),
            num_active=0, waiting=[], has_work=False,
            telemetry=RequestTelemetry(), stats={}, num_free_blocks=0)
        self.submitted = []

    def set_room(self, n: int) -> None:
        self.engine.cfg.max_seqs = n

    def submit(self, prompt_ids, params, request_id=None, q=None,
               trace_id=""):
        req = Request(request_id=request_id,
                      prompt_token_ids=list(prompt_ids),
                      params=params or SamplingParams(),
                      trace_id=trace_id)
        self.submitted.append(req)
        return req, q


def _gateway(room=0, registry=None, **overrides):
    fake = _FakeAsyncEngine(room=room)
    cfg = GatewayConfig(enabled=True, **overrides)
    gw = AdmissionGateway(fake, cfg, registry)
    return gw, fake


def _wait_for(cond, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.005)
    raise AssertionError(f"timed out waiting for {msg}")


def test_queue_bound_rejects_429_with_retry_after():
    gw, fake = _gateway(room=0, max_queued_requests=2, retry_after_s=3.0)
    try:
        gw.submit([1, 2], SamplingParams(), "r0")
        gw.submit([1, 2], SamplingParams(), "r1")
        with pytest.raises(AdmissionError) as ei:
            gw.submit([1, 2], SamplingParams(), "r2")
        assert ei.value.status == 429
        assert ei.value.retry_after == 3.0
        # Nothing reached the engine: the bound held the line pre-prefill.
        assert fake.submitted == []
    finally:
        gw.shutdown()


def test_queue_token_bound_rejects_429():
    gw, _ = _gateway(room=0, max_queued_requests=100, max_queued_tokens=10)
    try:
        gw.submit([0] * 6, SamplingParams(), "r0")
        with pytest.raises(AdmissionError) as ei:
            gw.submit([0] * 6, SamplingParams(), "r1")
        assert ei.value.status == 429
        assert "tokens" in ei.value.message
    finally:
        gw.shutdown()


def test_per_tenant_rate_limit_independent_buckets():
    gw, _ = _gateway(room=0, max_queued_requests=100,
                     rate_limit_rps=1.0, rate_limit_burst=2.0)
    try:
        gw.submit([1], SamplingParams(), "a0", tenant="A")
        gw.submit([1], SamplingParams(), "a1", tenant="A")
        with pytest.raises(AdmissionError) as ei:
            gw.submit([1], SamplingParams(), "a2", tenant="A")
        assert ei.value.status == 429
        # Deficit-derived Retry-After: ~1 token at 1 rps.
        assert 0 < ei.value.retry_after <= 1.1
        # Tenant B's bucket is untouched by A's burst.
        gw.submit([1], SamplingParams(), "b0", tenant="B")
        gw.submit([1], SamplingParams(), "b1", tenant="B")
    finally:
        gw.shutdown()


def test_weighted_fair_dequeue_across_tenants():
    gw, fake = _gateway(room=0, max_queued_requests=100,
                        tenant_weights="A:3,B:1")
    try:
        # A's whole burst lands first; fair dequeue must still interleave.
        for i in range(6):
            gw.submit([1], SamplingParams(), f"a{i}", tenant="A")
        for i in range(2):
            gw.submit([1], SamplingParams(), f"b{i}", tenant="B")
        fake.set_room(100)
        _wait_for(lambda: len(fake.submitted) == 8, msg="dispatch of 8")
        order = [r.request_id for r in fake.submitted]
        # Weight 3:1 -> among the first 4 dispatches, 3 of A to 1 of B
        # (stride scheduling), not A's entire FIFO burst.
        first4 = order[:4]
        assert sum(1 for rid in first4 if rid.startswith("a")) == 3, order
        assert sum(1 for rid in first4 if rid.startswith("b")) == 1, order
    finally:
        gw.shutdown()


def test_equal_weight_fairness_two_tenant_burst():
    gw, fake = _gateway(room=0, max_queued_requests=100)
    try:
        for i in range(4):
            gw.submit([1], SamplingParams(), f"a{i}", tenant="A")
        for i in range(4):
            gw.submit([1], SamplingParams(), f"b{i}", tenant="B")
        fake.set_room(100)
        _wait_for(lambda: len(fake.submitted) == 8, msg="dispatch of 8")
        order = ["ab"[r.request_id.startswith("b")]
                 for r in fake.submitted]
        # Unweighted tenants alternate: every prefix is within 1 of even.
        for k in range(1, 9):
            a, b = order[:k].count("a"), order[:k].count("b")
            assert abs(a - b) <= 1, order
    finally:
        gw.shutdown()


def test_priority_class_strictly_precedes_batch():
    gw, fake = _gateway(room=0, max_queued_requests=100)
    try:
        for i in range(3):
            gw.submit([1], SamplingParams(), f"batch{i}", priority="batch")
        for i in range(3):
            gw.submit([1], SamplingParams(), f"inter{i}",
                      priority="interactive")
        fake.set_room(100)
        _wait_for(lambda: len(fake.submitted) == 6, msg="dispatch of 6")
        order = [r.request_id for r in fake.submitted]
        assert order[:3] == ["inter0", "inter1", "inter2"], order
        assert all(rid.startswith("batch") for rid in order[3:]), order
    finally:
        gw.shutdown()


def test_unknown_priority_rejected():
    gw, _ = _gateway(room=0)
    try:
        with pytest.raises(AdmissionError) as ei:
            gw.submit([1], SamplingParams(), "r0", priority="urgent")
        assert ei.value.status == 400
    finally:
        gw.shutdown()


def test_queued_deadline_shed_before_prefill():
    registry = MetricsRegistry()
    gw, fake = _gateway(room=0, registry=registry, max_queued_requests=100)
    try:
        _, q = gw.submit([1, 2, 3], SamplingParams(), "r0", deadline_s=0.05)
        ev = q.get(timeout=5)
        assert ev[0] == "reject" and ev[1] == 503, ev
        assert "deadline" in ev[2]
        assert fake.submitted == []  # shed BEFORE any prefill
        shed = registry.counter("dlti_gateway_shed_total")
        # Sheds carry the priority label (per-class availability SLIs).
        assert shed.labels(priority="interactive").value >= 1
        stats = registry.stats_dict()
        assert stats["gateway_queue_depth"] == 0
        assert stats["gateway_queued_tokens"] == 0
    finally:
        gw.shutdown()


def test_deadline_mid_decode_sets_cancel_requested():
    gw, fake = _gateway(room=4, max_queued_requests=100)
    try:
        handle, _ = gw.submit([1, 2], SamplingParams(), "r0",
                              deadline_s=0.05)
        _wait_for(lambda: len(fake.submitted) == 1, msg="dispatch")
        req = fake.submitted[0]
        assert not req.cancel_requested
        _wait_for(lambda: req.cancel_requested, msg="deadline cancel")
        assert handle.cancel_requested
    finally:
        gw.shutdown()


def test_cancel_while_queued_never_reaches_engine():
    gw, fake = _gateway(room=0, max_queued_requests=100)
    try:
        handle, q = gw.submit([1, 2], SamplingParams(), "r0")
        handle.cancel_requested = True
        fake.set_room(10)
        ev = q.get(timeout=5)
        assert ev == ("done", "stop")
        assert fake.submitted == []
    finally:
        gw.shutdown()


def test_drain_refuses_new_admissions():
    gw, fake = _gateway(room=0, max_queued_requests=100)
    try:
        gw.submit([1], SamplingParams(), "r0")
        gw.drain()
        assert gw.draining
        with pytest.raises(AdmissionError) as ei:
            gw.submit([1], SamplingParams(), "r1")
        assert ei.value.status == 503
        assert "draining" in ei.value.message
        # Queued-pre-drain work still dispatches (accepted = finishes).
        fake.set_room(10)
        _wait_for(lambda: len(fake.submitted) == 1, msg="pre-drain dispatch")
    finally:
        gw.shutdown()


def test_gateway_metric_names_exposed():
    """Every contract name from GATEWAY_METRIC_NAMES appears in the
    Prometheus exposition once a labeled sample exists."""
    from dlti_tpu.serving.gateway import GATEWAY_METRIC_NAMES

    registry = MetricsRegistry()
    gw, _ = _gateway(room=0, registry=registry, max_queued_requests=1)
    try:
        gw.submit([1], SamplingParams(), "r0", tenant="T",
                  priority="interactive")
        with pytest.raises(AdmissionError):
            gw.submit([1], SamplingParams(), "r1")
        # Force the (labeled) shed series to exist without a real shed.
        gw._m_shed.labels(priority="interactive").inc(0)
        text = registry.render_prometheus()
        for name in GATEWAY_METRIC_NAMES:
            assert name in text, f"{name} missing from exposition"
        assert 'dlti_gateway_admitted_total{priority="interactive",tenant="T"} 1' in text
        assert ('dlti_gateway_rejected_total'
                '{priority="interactive",reason="queue_full"} 1') in text
    finally:
        gw.shutdown()


# ----------------------------------------------------------------------
# Cache-affinity routing units (fake replicas: no decode, no jit)
# ----------------------------------------------------------------------

def _fake_replicated(n: int, max_seqs: int = 4, spill_threshold: int = 4):
    """A ReplicatedEngine skeleton around load-controllable fakes — the
    routing logic under test is pure host code over engines' load/cfg."""

    def _mk(i):
        eng = types.SimpleNamespace(
            idx=i, waiting=[], num_active=0,
            cfg=types.SimpleNamespace(max_seqs=max_seqs))
        eng.submit = lambda ids, params, rid, trace_id="", _e=eng: (
            types.SimpleNamespace(request_id=rid, engine=_e,
                                  trace_id=trace_id))
        return eng

    import itertools

    rep = ReplicatedEngine.__new__(ReplicatedEngine)
    rep.engines = [_mk(i) for i in range(n)]
    rep._dead = set()
    rep._draining = set()
    rep._rr = 0
    rep._req_counter = itertools.count()
    rep.affinity_spill_threshold = spill_threshold
    rep.affinity = {"sticky": 0, "spill": 0}
    return rep


def test_affinity_rendezvous_is_sticky_and_spreads():
    rep = _fake_replicated(3)
    keys = [f"sess-{i}" for i in range(30)]
    owner = {k: rep._sticky_target(k, rep.live_engines()).idx for k in keys}
    # Deterministic: resubmitting a key always lands on the same replica.
    for k in keys:
        req = rep.submit([1, 2, 3], SamplingParams(), f"r-{k}",
                         affinity_key=k)
        assert req.engine.idx == owner[k]
    assert rep.affinity["sticky"] == 30 and rep.affinity["spill"] == 0
    # And it actually spreads sessions (not a degenerate hash).
    assert len(set(owner.values())) == 3


def test_affinity_rendezvous_stable_under_replica_death():
    """Killing one replica re-ranks ONLY the keys it owned — every other
    session keeps its (warm) target. The property that makes failover
    cheap for the fleet's caches."""
    rep = _fake_replicated(3)
    keys = [f"sess-{i}" for i in range(60)]
    before = {k: rep._sticky_target(k, rep.live_engines()).idx for k in keys}
    rep._dead.add(1)
    after = {k: rep._sticky_target(k, rep.live_engines()).idx for k in keys}
    for k in keys:
        if before[k] != 1:
            assert after[k] == before[k], f"{k} moved off a live replica"
        else:
            assert after[k] in (0, 2)  # orphans re-rank to survivors


def test_affinity_spills_least_loaded_past_backlog_threshold():
    rep = _fake_replicated(2, max_seqs=2, spill_threshold=1)
    key = "sess-hot"
    sticky = rep._sticky_target(key, rep.live_engines())
    other = next(e for e in rep.engines if e is not sticky)
    # Backlog = load - max_seqs = 4 - 2 = 2 > threshold 1: spill.
    sticky.num_active = 2
    sticky.waiting = [object(), object()]
    req = rep.submit([1], SamplingParams(), "r0", affinity_key=key)
    assert req.engine is other
    assert rep.affinity == {"sticky": 0, "spill": 1}
    # Backlog back under threshold: sticky again.
    sticky.waiting = []
    req = rep.submit([1], SamplingParams(), "r1", affinity_key=key)
    assert req.engine is sticky
    assert rep.affinity == {"sticky": 1, "spill": 1}


def test_affinity_key_from_headers_and_prefix():
    from dlti_tpu.serving.gateway import affinity_key_from

    # X-Session wins over the prompt digest.
    assert affinity_key_from({"X-Session": "abc "}, [1, 2, 3]) == "sess-abc"
    # Session-less: same prompt prefix -> same key, regardless of tail.
    k1 = affinity_key_from({}, list(range(64)), prefix_tokens=32)
    k2 = affinity_key_from({}, list(range(32)) + [99] * 32, prefix_tokens=32)
    k3 = affinity_key_from({}, [7] + list(range(63)), prefix_tokens=32)
    assert k1 == k2 and k1 != k3 and k1.startswith("pfx-")


# ----------------------------------------------------------------------
# Full-stack integration (real engine + HTTP)
# ----------------------------------------------------------------------

def _tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _start_server(engine, gw_cfg, request_timeout_s=120.0):
    httpd, async_engine = make_server(
        engine, IdTokenizer(vocab_size=CFG.vocab_size),
        ServerConfig(host="127.0.0.1", port=0,
                     request_timeout_s=request_timeout_s,
                     default_params=SamplingParams(max_tokens=8),
                     gateway=gw_cfg))
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    return httpd, async_engine, httpd.server_address[1]


def _stop_server(httpd, async_engine):
    httpd.shutdown()
    if httpd.gateway is not None:
        httpd.gateway.shutdown()
    async_engine.shutdown()
    httpd.server_close()


def _post(port, path, body, headers=None):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json", **(headers or {})})
    resp = conn.getresponse()
    data = resp.read()
    out_headers = dict(resp.getheaders())
    conn.close()
    return resp.status, data, out_headers


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_loadgen_burst_sheds_429_accepted_complete():
    """Acceptance: a burst past the queue bound sheds with 429 +
    Retry-After while accepted requests complete normally."""
    from dlti_tpu.benchmarks import LoadGenConfig, run_load_test

    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, _tiny_params(), ec)
    gw_cfg = GatewayConfig(enabled=True, max_queued_requests=3,
                           retry_after_s=2.0)
    httpd, aeng, port = _start_server(engine, gw_cfg)
    try:
        report = run_load_test(LoadGenConfig(
            host="127.0.0.1", port=port, num_requests=24, concurrency=24,
            max_tokens=16, stream=False, prompt="burst", timeout_s=120))
        # Every request either completed or was deliberately shed — the
        # burst produced no real errors.
        assert report.num_ok + report.num_shed == 24, report.errors
        assert report.num_ok >= 1
        assert report.num_shed >= 1, "burst never exceeded the queue bound"
        assert report.shed_rate == pytest.approx(report.num_shed / 24,
                                                 abs=1e-4)
        assert report.errors == [], report.errors
        # Direct probe for the Retry-After header on a shed response:
        # stall the queue (slots busy with the long default) then overfill.
        status, data, headers = _post(port, "/v1/completions", {
            "prompt": "x", "max_tokens": 1, "temperature": 0.0})
        assert status == 200, data
    finally:
        _stop_server(httpd, aeng)


def test_loadgen_multitenant_priority_mix_report():
    """Satellite: --tenants/--priority-mix drive the gateway end to end
    and the report carries per-class latency percentiles."""
    from dlti_tpu.benchmarks import LoadGenConfig, run_load_test

    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, _tiny_params(), ec)
    gw_cfg = GatewayConfig(enabled=True, max_queued_requests=64)
    httpd, aeng, port = _start_server(engine, gw_cfg)
    try:
        report = run_load_test(LoadGenConfig(
            host="127.0.0.1", port=port, num_requests=12, concurrency=4,
            max_tokens=4, stream=True, prompt="mix", timeout_s=120,
            tenants=3, priority_mix="interactive:0.5,batch:0.5"))
        assert report.num_ok == 12, report.errors
        assert set(report.per_class) == {"interactive", "batch"}
        total = sum(c["count"] for c in report.per_class.values())
        assert total == 12
        for cls in report.per_class.values():
            if cls["ok"]:
                assert cls["ttft_p50_s"] > 0
        # Both priority classes and all three tenants hit the gateway.
        stats = json.loads(_get(port, "/stats")[1])
        keys = [k for k in stats
                if k.startswith("dlti_gateway_admitted_total")]
        assert any("tenant-0" in k for k in keys), keys
        assert any("tenant-2" in k for k in keys), keys
    finally:
        _stop_server(httpd, aeng)


def test_http_429_carries_retry_after_header():
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, _tiny_params(), ec)
    # Deterministic refusal: burst capacity 1 at a glacial refill.
    gw_cfg = GatewayConfig(enabled=True, rate_limit_rps=0.01,
                           rate_limit_burst=1.0)
    httpd, aeng, port = _start_server(engine, gw_cfg)
    try:
        status, data, _ = _post(port, "/v1/completions",
                                {"prompt": "a", "max_tokens": 2})
        assert status == 200, data
        status, data, headers = _post(port, "/v1/completions",
                                      {"prompt": "a", "max_tokens": 2})
        assert status == 429, data
        assert "rate limit" in json.loads(data)["error"]["message"]
        assert int(headers["Retry-After"]) >= 1
        # The unlimited default tenant is a different principal: an
        # X-Tenant'd client refusal never blocks another tenant.
        status, _, _ = _post(port, "/v1/completions",
                             {"prompt": "a", "max_tokens": 2},
                             headers={"X-Tenant": "other"})
        assert status == 200
    finally:
        _stop_server(httpd, aeng)


def test_drain_flips_health_and_finishes_inflight():
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, _tiny_params(), ec)
    gw_cfg = GatewayConfig(enabled=True, drain_grace_s=30.0)
    httpd, aeng, port = _start_server(engine, gw_cfg)
    try:
        assert _get(port, "/health")[0] == 200
        results = {}

        def _inflight():
            results["resp"] = _post(port, "/v1/completions", {
                "prompt": "abc", "max_tokens": 24, "temperature": 0.0})

        t = threading.Thread(target=_inflight)
        t.start()
        # Wait until the request is actually in the system, then drain —
        # the same sequence serve()'s SIGTERM handler runs.
        _wait_for(lambda: engine.has_work, msg="in-flight request")
        httpd.gateway.drain()
        status, data = _get(port, "/health")
        assert status == 503
        assert json.loads(data)["status"] == "draining"
        status, data, headers = _post(port, "/v1/completions",
                                      {"prompt": "new", "max_tokens": 2})
        assert status == 503
        assert "draining" in json.loads(data)["error"]["message"]
        assert "Retry-After" in headers
        t.join(timeout=60)
        assert results["resp"][0] == 200, "in-flight request must finish"
        assert httpd.gateway.wait_idle(30.0)
    finally:
        _stop_server(httpd, aeng)


def test_health_reports_dead_engine():
    """Satellite: /health must 503 once the stepper parks itself — a load
    balancer kept routing to a corpse on the old unconditional 200."""
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, _tiny_params(), ec)
    httpd, aeng, port = _start_server(engine, None)
    try:
        assert _get(port, "/health")[0] == 200
        aeng._dead = True  # the state abort-failure recovery leaves behind
        status, data = _get(port, "/health")
        assert status == 503
        assert json.loads(data)["status"] == "dead"
    finally:
        aeng._stop = True
        _stop_server(httpd, aeng)


def test_request_timeout_cancels_engine_request():
    """Satellite: request_timeout_s expiry must set cancel_requested —
    the engine releases the slot instead of decoding to max_tokens."""
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(CFG, _tiny_params(), ec)
    httpd, aeng, port = _start_server(engine, None, request_timeout_s=0.05)
    try:
        status, data, _ = _post(port, "/v1/completions", {
            "prompt": "abc", "max_tokens": 100, "temperature": 0.0})
        assert status == 500
        assert "timed out" in json.loads(data)["error"]["message"]
        # The cancel drains the request within one decode window: the
        # engine empties long before 100 tokens' worth of steps.
        _wait_for(lambda: not engine.has_work, timeout=30,
                  msg="engine drained after timeout cancel")
        req = next(r for r in engine.finished)
        assert len(req.output_token_ids) < 100
    finally:
        _stop_server(httpd, aeng)


# ----------------------------------------------------------------------
# Replica failover
# ----------------------------------------------------------------------

def test_replica_fault_fails_over_offline_generate(devices):
    """Satellite: one replica's step() fault must not orphan the other
    replica's requests — stranded requests finish on the survivor."""
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    rep = ReplicatedEngine(CFG, _tiny_params(), ec, replicas=2, tensor=1,
                           devices=devices[:2], max_retries=2,
                           fault_inject_step="0:2")
    prompts = [[1, 2, 3], [4, 5], [6, 7, 8], [9, 10]]
    results = rep.generate(prompts, SamplingParams(max_tokens=6,
                                                   temperature=0.0))
    assert rep.num_live == 1
    assert rep.failover["replica_faults"] == 1
    assert rep.failover["retries"] >= 1
    for r in results:
        assert r.finish_reason == "length", r
        assert len(r.output_token_ids) == 6
    # The survivor keeps serving new work.
    more = rep.generate([[2, 4, 6]], SamplingParams(max_tokens=3,
                                                    temperature=0.0))
    assert more[0].finish_reason == "length"


def test_replica_fault_exhausted_retries_error_not_hang(devices):
    """Both replicas down: requests finish as errors instead of hanging
    the drain loop or crashing the caller."""
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    rep = ReplicatedEngine(CFG, _tiny_params(), ec, replicas=2, tensor=1,
                           devices=devices[:2], max_retries=2)
    for eng in rep.engines:
        eng.step = lambda: (_ for _ in ()).throw(
            RuntimeError("injected: both replicas die"))
    results = rep.generate([[1, 2, 3], [4, 5, 6]],
                           SamplingParams(max_tokens=4))
    assert rep.num_live == 0
    assert all(r.finish_reason in ("error", "abort") for r in results)
    with pytest.raises(RuntimeError):
        rep.submit([1, 2], SamplingParams())


def test_replica_warmup_aot_stays_engaged_off_default_device(devices):
    """Regression (found driving scripts/serve.py --replicas 2): warmup's
    AOT lowering must carry each replica's actual placement — lowered on
    plain avals it compiled for device 0, and replica 1's pinned params
    made its first decode step raise a sharding-mismatch ValueError that
    read as a replica fault and killed the replica at startup. Both
    replicas must warm up, keep the AOT dispatch path, and emit the same
    greedy stream."""
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    params = _tiny_params()
    rep = ReplicatedEngine(CFG, params, ec, replicas=2, tensor=1,
                           devices=devices[:2])
    rep.warmup_decode_ladder()
    res = rep.generate([[1, 2, 3], [4, 5, 6]],
                       SamplingParams(max_tokens=5, temperature=0.0))
    assert rep.num_live == 2 and rep.failover["replica_faults"] == 0
    for eng in rep.engines:
        assert eng._decode_fn._aot_state["aot"], \
            "replica fell off the AOT decode path"
    # Placement agrees end to end: each replica's KV pool is committed to
    # its own params' device (jit migration no longer papers over it).
    for eng in rep.engines:
        p_dev = next(iter(jax.tree_util.tree_leaves(eng.params)[0].devices()))
        c_dev = next(iter(jax.tree_util.tree_leaves(eng.cache)[0].devices()))
        assert p_dev == c_dev
    single = InferenceEngine(CFG, params, ec).generate(
        [[1, 2, 3]], SamplingParams(max_tokens=5, temperature=0.0))
    assert single[0].output_token_ids == res[0].output_token_ids


def test_replica_kill_failover_through_server(devices):
    """Acceptance: with affinity routing on and one replica fault-injected
    mid-run, its in-flight requests complete on the survivor — client
    error rate from the fault is 0, the retries are visible in
    dlti_gateway_retries_total, and sessions that were sticky to the dead
    replica re-route to the survivor and still complete."""
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    rep = ReplicatedEngine(CFG, _tiny_params(), ec, replicas=2, tensor=1,
                           devices=devices[:2], max_retries=2,
                           fault_inject_step="0:3")
    gw_cfg = GatewayConfig(enabled=True, max_queued_requests=64,
                           affinity=True)
    # With 2 replicas, 6 sessions hash to both sides — some are sticky to
    # the replica the chaos hook is about to kill.
    sessions = [f"sess-{i}" for i in range(6)]
    doomed = [s for s in sessions
              if rep._sticky_target("sess-" + s, rep.live_engines())
              is rep.engines[0]]
    assert doomed, "rendezvous hash left replica 0 unused; test is vacuous"
    httpd, aeng, port = _start_server(rep, gw_cfg)
    try:
        results = [None] * 6

        def _one(i):
            results[i] = _post(
                port, "/v1/completions",
                {"prompt": f"req {i}", "max_tokens": 12, "temperature": 0.0},
                headers={"X-Session": sessions[i]})

        threads = [threading.Thread(target=_one, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        for i, r in enumerate(results):
            assert r is not None and r[0] == 200, (i, r)
            obj = json.loads(r[1])
            assert obj["usage"]["completion_tokens"] == 12, obj
        assert rep.num_live == 1
        assert rep.failover["retries"] >= 1
        assert rep.affinity["sticky"] >= 1

        # Sessions sticky to the DEAD replica re-route: rendezvous over
        # the survivors now owns them, and their follow-up turns complete
        # with zero client errors.
        for s in doomed:
            status, data, _ = _post(
                port, "/v1/completions",
                {"prompt": f"follow-up {s}", "max_tokens": 6,
                 "temperature": 0.0},
                headers={"X-Session": s})
            assert status == 200, (s, status, data)
            assert json.loads(data)["usage"]["completion_tokens"] == 6

        # Retries + affinity counters are on /metrics under contract names.
        status, data = _get(port, "/metrics")
        assert status == 200
        text = data.decode()
        line = next(l for l in text.splitlines()
                    if l.startswith("dlti_gateway_retries_total "))
        assert float(line.split()[1]) >= 1
        line = next(l for l in text.splitlines()
                    if l.startswith("dlti_gateway_replicas_alive "))
        assert float(line.split()[1]) == 1
        line = next(l for l in text.splitlines()
                    if l.startswith("dlti_gateway_affinity_sticky_total "))
        assert float(line.split()[1]) >= 1
    finally:
        _stop_server(httpd, aeng)
