"""Adaptive speculative decoding as a production citizen — tier 1.

Speculation is output-invariant by construction (greedy-exact verify);
these tests pin that invariance where it is easiest to lose — at every
production seam — plus the per-slot controller semantics themselves:

* **Per-slot gating**: a zero-ngram-hit slot pauses alone while a
  repetitive-text slot in the SAME batch keeps accepting drafts (the
  batch-wide `_spec_pause` this controller replaced would have stalled
  both).
* **Draft-length ladder**: sustained low acceptance walks dispatch k
  down the pow2 ladder; `spec_adaptive=False` pins k at
  `num_draft_tokens`.
* **Handoff carry**: the controller window/cooldown/EWMA ride
  `export_handoff` → wire envelope → `adopt_handoff` byte-exactly, so
  an adopting engine resumes the gate mid-window instead of re-probing.
* **Equivalence cells**: spec on == spec off, token-for-token, across
  {disagg on/off} × {bf16, int8 KV}, a 2-worker fleet with a planned
  mid-decode drain migration, a multi-LoRA batch vs merged-weights
  oracles, and prefix-tier restores.
* **Ragged prefill**: total-token-bucketed multi-admission packing is
  byte-identical to per-bucket prefill while issuing fewer device calls,
  in both throughput and chunked admission modes.

The tiny random model is the test vocabulary: greedy generation after
``[6, 6, 7, 7, ...]`` locks into a period-1 loop (sustained ngram hits,
~100% acceptance) while ``[2, 7, 1, 8, 2, 8]`` emits distinct tokens
for its first several rounds (zero lookup hits) — a deterministic
favorable/adversarial pair.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import unfreeze

from dlti_tpu.config import LoRAConfig, MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.models.lora import merge_lora_params
from dlti_tpu.serving import (
    DisaggController, EngineConfig, InferenceEngine, SamplingParams,
)
from dlti_tpu.serving import wire
from dlti_tpu.serving.adapters import (
    get_catalog, register_adapter, save_adapter,
)

CFG = MODEL_PRESETS["llama_tiny"]

CYCLIC = [6, 6, 7, 7, 6, 6, 7, 7]      # generation loops -> accepts
ACYCLIC = [2, 7, 1, 8, 2, 8]           # no early hits -> pauses
SPEC_PROMPTS = [CYCLIC, [1, 2, 3, 4, 5], ACYCLIC, [5, 5, 5, 5]]

GREEDY = SamplingParams(max_tokens=8, temperature=0.0)


@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _ec(**over):
    base = dict(max_seqs=4, block_size=8, num_blocks=64, max_model_len=128,
                cache_dtype="float32", eos_token_id=-1, speculative="ngram")
    base.update(over)
    return EngineConfig(**base)


def _drain(eng, reqs):
    while eng.has_work:
        eng.step()
    return reqs


def _plain_outputs(params, prompts, sp, **over):
    eng = InferenceEngine(CFG, params, _ec(speculative="none", **over))
    return [r.output_token_ids for r in eng.generate(prompts, sp)]


# ----------------------------------------------------------------------
# Per-slot controller semantics
# ----------------------------------------------------------------------

def test_zero_hit_slot_pauses_alone(tiny_params):
    """The headline of the per-slot gate: the adversarial slot burns its
    probe window on zero-hit rounds and pauses, while the cyclic slot in
    the SAME batch keeps proposing and accepting the whole run."""
    ec = _ec(max_seqs=2, num_blocks=128, max_model_len=256,
             spec_probe_window=6, spec_cooldown=10_000)
    eng = InferenceEngine(CFG, tiny_params, ec)
    sp = SamplingParams(temperature=0.0, max_tokens=48)
    fav = eng.submit(CYCLIC, sp)
    adv = eng.submit(ACYCLIC, sp)
    eng.step()  # slots assigned at first admission step
    sid = {s.request.request_id: s.slot_id for s in eng.slots if s.request}
    fav_paused = adv_paused = False
    while eng.has_work:
        eng.step()
        fav_paused |= bool(eng._spec_slot_pause[sid[fav.request_id]] > 0)
        adv_paused |= bool(eng._spec_slot_pause[sid[adv.request_id]] > 0)
    assert adv_paused and not fav_paused
    assert eng.stats["spec_paused_rounds"] > 0
    assert eng.stats["spec_accepted"] > 0  # the cyclic slot kept winning
    # Gating is a throughput decision, never an output one.
    expect = _plain_outputs(tiny_params, [CYCLIC, ACYCLIC], sp,
                            max_seqs=2, num_blocks=128, max_model_len=256)
    assert [fav.output_token_ids, adv.output_token_ids] == expect


def test_released_slot_forgets_controller_state(tiny_params):
    """Slot reuse must not inherit the previous tenant's cooldown or a
    half-filled acceptance window."""
    ec = _ec(max_seqs=1, spec_probe_window=4, spec_cooldown=10_000)
    eng = InferenceEngine(CFG, tiny_params, ec)
    req = eng.submit(ACYCLIC, SamplingParams(temperature=0.0, max_tokens=24))
    _drain(eng, [req])
    assert req.finish_reason == "length"
    assert int(eng._spec_slot_pause[0]) == 0
    assert int(eng._spec_slot_prop[0]) == 0
    assert int(eng._spec_slot_acc[0]) == 0
    assert float(eng._spec_slot_ewma[0]) == float(ec.num_draft_tokens)


def test_adaptive_ladder_shrinks_draft_len(tiny_params):
    """Sustained low acceptance walks dispatch k down the pow2 ladder
    (compiling the smaller program lazily); spec_adaptive=False keeps
    every dispatch at num_draft_tokens."""
    sp = SamplingParams(temperature=0.0, max_tokens=48)
    ec = _ec(max_seqs=1, num_blocks=128, max_model_len=256,
             spec_min_acceptance=0.0)  # gate off: isolate the ladder
    eng = InferenceEngine(CFG, tiny_params, ec)
    eng.submit(ACYCLIC, sp)
    ks = set()
    while eng.has_work:
        eng.step()
        ks.add(int(eng.spec_draft_len))
    dispatched = ks - {0}
    assert dispatched, "speculation never dispatched"
    assert min(dispatched) < ec.num_draft_tokens
    # The smaller rung is a real compiled program in the ladder cache.
    assert set(eng.executor._spec_fns) >= {ec.num_draft_tokens,
                                           min(dispatched)}
    fixed = InferenceEngine(CFG, tiny_params,
                            _ec(max_seqs=1, num_blocks=128,
                                max_model_len=256, spec_min_acceptance=0.0,
                                spec_adaptive=False))
    fixed.submit(ACYCLIC, sp)
    fks = set()
    while fixed.has_work:
        fixed.step()
        fks.add(int(fixed.spec_draft_len))
    assert fks - {0} == {ec.num_draft_tokens}


# ----------------------------------------------------------------------
# Handoff carry: the controller rides the envelope
# ----------------------------------------------------------------------

def test_handoff_carries_spec_state_across_wire(tiny_params):
    src = InferenceEngine(CFG, tiny_params, _ec())
    src.prefill_only = True
    req = src.submit(CYCLIC, SamplingParams(temperature=0.0, max_tokens=8))
    for _ in range(50):
        src.step()
        slot = next((s for s in src.slots if s.request is req), None)
        if slot is not None and not slot.prefilling \
                and slot.last_token is not None:
            break
    else:
        pytest.fail("prefill never completed")
    # Mid-window controller state (a prefill-only engine never decodes,
    # so plant a distinctive snapshot the export must carry verbatim).
    sid = slot.slot_id
    src._spec_slot_prop[sid] = 5
    src._spec_slot_acc[sid] = 3
    src._spec_slot_pause[sid] = 2
    src._spec_slot_ewma[sid] = 1.5
    snap = src.export_handoff(slot)
    assert snap["spec"] == {"prop": 5, "acc": 3, "pause": 2, "ewma": 1.5}
    # Export released the origin slot back to the fresh-slot state.
    assert int(src._spec_slot_prop[sid]) == 0
    # The additive dict survives the generic wire envelope byte-exactly.
    snap2 = wire.unpack_handoff(wire.pack_handoff(snap))
    assert snap2["spec"] == snap["spec"]
    dst = InferenceEngine(CFG, tiny_params, _ec())
    assert dst.adopt_handoff(snap2)
    dslot = next(s for s in dst.slots if s.request.request_id
                 == req.request_id)
    did = dslot.slot_id
    assert int(dst._spec_slot_prop[did]) == 5
    assert int(dst._spec_slot_acc[did]) == 3
    assert int(dst._spec_slot_pause[did]) == 2
    assert float(dst._spec_slot_ewma[did]) == 1.5


# ----------------------------------------------------------------------
# Equivalence cells: spec on == spec off at every production seam
# ----------------------------------------------------------------------

@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
def test_spec_outputs_identical_disagg_on_vs_off(tiny_params, devices,
                                                 kv_dtype):
    """Spec × disagg × KV dtype: the speculating decode pool finishes
    adopted prefills token-identically to a plain colocated engine."""
    sp = GREEDY
    expect = _plain_outputs(tiny_params, SPEC_PROMPTS, sp,
                            cache_dtype=kv_dtype)
    solo = InferenceEngine(CFG, tiny_params, _ec(cache_dtype=kv_dtype))
    got = [r.output_token_ids for r in solo.generate(SPEC_PROMPTS, sp)]
    assert got == expect
    assert solo.stats["spec_proposed"] > 0  # speculation genuinely ran
    ctl = DisaggController(CFG, tiny_params, _ec(cache_dtype=kv_dtype),
                           prefill_replicas=1, decode_replicas=2,
                           devices=devices[:3])
    got = [r.output_token_ids for r in ctl.generate(SPEC_PROMPTS, sp)]
    assert got == expect
    assert ctl.handoff["completed"] >= len(SPEC_PROMPTS)
    assert sum(e.stats["spec_proposed"]
               for e in ctl.decode.engines) > 0


def test_spec_fleet_migration_byte_identical(tiny_params):
    """Spec × fleet × planned drain: a speculating 2-worker fleet, one
    worker drained mid-decode, still lands the single-engine tokens —
    the controller state crosses the process-shaped boundary with the
    KV envelope."""
    import threading

    from dlti_tpu.config import FleetConfig, ReplicaLifecycleConfig
    from dlti_tpu.serving.fleet import FleetSupervisor
    from dlti_tpu.serving.worker import EngineWorker

    sp = SamplingParams(max_tokens=12, temperature=0.0)
    expect = _plain_outputs(tiny_params, SPEC_PROMPTS, sp)

    class _Handle:
        def __init__(self, worker):
            self.worker = worker
            self.pid = 990000 + worker.worker_id
            self.thread = threading.Thread(target=worker.serve_forever,
                                           daemon=True)
            self.thread.start()

        def port(self):
            return self.worker.port

        def poll(self):
            return None if self.thread.is_alive() else 0

        def wait(self, timeout=None):
            self.thread.join(timeout)
            return 0

        def terminate(self):
            self.worker.close()

        kill = terminate

    def spawn(idx, generation):
        engine = InferenceEngine(CFG, tiny_params, _ec())
        return _Handle(EngineWorker(engine, port=0, worker_id=idx))

    sup = FleetSupervisor(
        _ec(), workers=2, spawner=spawn,
        fleet_cfg=FleetConfig(workers=2, health_interval_s=0.05,
                              respawn_backoff_s=0.05,
                              respawn_backoff_max_s=0.5,
                              startup_timeout_s=120.0, rpc_timeout_s=60.0,
                              term_grace_s=2.0),
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=True,
                                             probation_initial_s=0.05,
                                             probation_max_s=0.5),
        canary_vocab=CFG.vocab_size)
    try:
        reqs = [sup.submit(p, sp) for p in SPEC_PROMPTS]
        for _ in range(60):
            sup.step()
            if all(len(r.output_token_ids) >= 2 for r in reqs):
                break
        assert all(not r.done for r in reqs)
        victim = next(w for w in sup._workers if w.owned)
        errored = sup.drain_replica(victim.idx, kind="preempt",
                                    quarantine=False)
        assert errored == []
        while sup.has_work:
            sup.step()
        assert [r for r in reqs if r.num_migrations > 0], \
            "drain must migrate at least one mid-decode request"
        for p, r in zip(SPEC_PROMPTS, reqs):
            assert r.output_token_ids == expect[SPEC_PROMPTS.index(p)], \
                f"{r.request_id} (migrations={r.num_migrations})"
            assert r.finish_reason == "length"
    finally:
        sup.close()


@pytest.fixture()
def _clean_catalog():
    get_catalog().clear()
    yield
    get_catalog().clear()


def test_spec_multilora_matches_merged_engines(tmp_path, _clean_catalog):
    """Spec × multi-LoRA: a speculating shared-base engine serving a
    heterogeneous adapter batch emits the same tokens as per-adapter
    merged-weights engines running WITHOUT speculation."""
    R, ALPHA = 4, 8.0
    model = LlamaForCausalLM(CFG, LoRAConfig(r=R, alpha=int(ALPHA),
                                             dropout=0.0))
    tree = unfreeze(model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), jnp.int32))["params"])

    def _randomize(node, rng):
        for k in node:
            v = node[k]
            if not isinstance(v, dict):
                continue
            if "lora_a" in v and "lora_b" in v:
                v["lora_a"] = jnp.asarray(
                    rng.normal(0.0, 0.2, np.shape(v["lora_a"])), jnp.float32)
                v["lora_b"] = jnp.asarray(
                    rng.normal(0.0, 0.2, np.shape(v["lora_b"])), jnp.float32)
            else:
                _randomize(v, rng)

    _randomize(tree, np.random.RandomState(1))
    base = merge_lora_params(tree, scaling=0.0)
    merged = merge_lora_params(tree, alpha=ALPHA)
    d = str(tmp_path / "ad-s")
    save_adapter(d, tree, alpha=ALPHA)
    register_adapter("ad-s", d)

    sp = SamplingParams(temperature=0.0, max_tokens=16)
    ec = _ec(max_model_len=64, adapter_slots=2, adapter_rank=R)
    shared = InferenceEngine(CFG, base, ec)
    # The base row is the cyclic one: adapter weights steer generation
    # away from the loop, and the engagement assert below needs at least
    # one row that genuinely accepts drafts.
    assign = [(CYCLIC, ""), ([5, 5, 5, 5], "ad-s"), (ACYCLIC, "ad-s")]
    reqs = [shared.submit(p, sp, adapter=name) for p, name in assign]
    _drain(shared, reqs)
    assert shared.stats["spec_proposed"] > 0
    oracle = {
        "": InferenceEngine(CFG, base,
                            _ec(max_model_len=64, speculative="none")),
        "ad-s": InferenceEngine(CFG, merged,
                                _ec(max_model_len=64, speculative="none")),
    }
    for (prompt, name), req in zip(assign, reqs):
        want = oracle[name].generate([prompt], sp)[0]
        assert req.output_token_ids == want.output_token_ids, name


def test_spec_prefix_tier_restore_byte_identical(tiny_params, tmp_path):
    """Spec × prefix tiering: host-tier restores feed a speculating
    engine the exact cached KV, so revisited sessions stay
    token-identical to an uncached, unspeculative engine."""
    # 4 "sessions": shared 8-token block + per-session block + tail — a
    # 7-block device pool cannot hold all of them at once, so round 2
    # revisits blocks the host/disk tiers absorbed.
    sessions = [[i] * 8 + [7] * 8 + [1, 2, 3] for i in range(4)]
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    tiered = InferenceEngine(
        CFG, tiny_params,
        _ec(max_seqs=1, num_blocks=7, max_model_len=40,
            enable_prefix_caching=True, prefix_host_blocks=8,
            prefix_disk_dir=str(tmp_path), prefix_disk_blocks=16))
    plain = InferenceEngine(
        CFG, tiny_params,
        _ec(max_seqs=1, num_blocks=7, max_model_len=40,
            speculative="none"))
    for _ in range(2):  # round 2 revisits everything the pool evicted
        for p in sessions:
            [got] = tiered.generate([p], sp)
            [want] = plain.generate([p], sp)
            assert got.output_token_ids == want.output_token_ids
    assert tiered.stats["prefix_restored_tokens"] > 0


# ----------------------------------------------------------------------
# Ragged multi-admission prefill
# ----------------------------------------------------------------------

RAGGED_PROMPTS = [list(range(2, 2 + n)) for n in (5, 3, 9, 2, 17, 4)]


@pytest.mark.parametrize("mode", ["throughput", "chunked"])
def test_ragged_prefill_byte_identical_with_fewer_batches(tiny_params,
                                                          mode):
    over = dict(max_seqs=8, speculative="none")
    if mode == "chunked":
        over["max_prefill_tokens_per_step"] = 16
    sp = SamplingParams(temperature=0.0, max_tokens=6)

    def run(ragged):
        eng = InferenceEngine(CFG, tiny_params,
                              _ec(ragged_prefill=ragged, **over))
        reqs = [eng.submit(p, sp) for p in RAGGED_PROMPTS]
        _drain(eng, reqs)
        outs = [(r.output_token_ids, [float(x) for x in r.output_logprobs])
                for r in reqs]
        return outs, eng.stats["prefill_batches"]

    off_outs, off_batches = run(False)
    on_outs, on_batches = run(True)
    assert on_outs == off_outs  # tokens AND logprobs, byte-for-byte
    assert on_batches < off_batches  # packing genuinely merged calls
