"""fp16 dynamic loss scaling + ZeRO-3 param host offload.

DeepSpeed parity targets: the fp16 block of ``configs/ds_config_zero1.json:25-32``
(dynamic scale, initial 2^16, window, hysteresis, min scale) and the ZeRO-3
param/optimizer CPU offload of ``configs/ds_config_zero3.json:19-27``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    OptimizerConfig, ParallelConfig, TrainConfig, ZeROStage,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.parallel import build_mesh, make_sharded_train_step, shard_train_state
from dlti_tpu.training import build_optimizer, create_train_state, make_train_step

CFG = MODEL_PRESETS["llama_tiny"]


def _mk_state(fp16_scale=None, lora=True):
    model = LlamaForCausalLM(CFG, LoRAConfig(r=4, alpha=8, dropout=0.0) if lora else None)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))  # lr > 0 at step 1
    return model, create_train_state(
        jax.random.PRNGKey(0), model, tx, (2, 16), lora_enabled=lora,
        fp16_initial_scale=fp16_scale)


def _batch(rng, accum=1, bs=2, seq=16):
    return {
        "input_ids": jax.random.randint(rng, (accum, bs, seq), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((accum, bs, seq), jnp.int32),
    }


def test_scaler_state_initialized():
    _, state = _mk_state(fp16_scale=2.0 ** 16)
    assert float(state.scaler["scale"]) == 65536.0
    assert int(state.scaler["hysteresis_left"]) == 2
    _, state = _mk_state(fp16_scale=None)
    assert state.scaler is None


def test_fp16_step_trains_and_reports_scale(rng):
    model, state = _mk_state(fp16_scale=2.0 ** 4)
    step = jax.jit(make_train_step(model, accum_steps=1, fp16_scale_window=2))

    def lora_b(s):
        # lora_b gets nonzero grads at step 1 (lora_a's are zero while B=0).
        return np.asarray(
            s.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])

    before = lora_b(state)
    state, m = step(state, _batch(rng), rng)
    assert float(m["overflow"]) == 0.0
    assert float(m["loss_scale"]) == 16.0
    assert not np.allclose(before, lora_b(state))
    # Window of consecutive good steps doubles the scale.
    state, m = step(state, _batch(rng), rng)
    assert float(m["loss_scale"]) == 32.0
    assert int(state.scaler["good_steps"]) == 0


def test_fp16_overflow_skips_update_and_shrinks_after_hysteresis(rng):
    model, state = _mk_state(fp16_scale=2.0 ** 8)
    step = jax.jit(make_train_step(model, accum_steps=1, fp16_hysteresis=2,
                                   fp16_scale_window=1000))
    bad = _batch(rng)
    # Poison one LoRA factor so grads are NaN.
    params = state.params
    params["model"]["layers_0"]["attn"]["q_proj"]["lora_a"] = (
        params["model"]["layers_0"]["attn"]["q_proj"]["lora_a"].at[0, 0].set(jnp.nan))
    state = state.replace(params=params)
    opt_before = jax.tree_util.tree_leaves(state.opt_state)
    state, m = step(state, bad, rng)
    assert float(m["overflow"]) == 1.0
    # First overflow: hysteresis absorbs it, scale unchanged.
    assert float(m["loss_scale"]) == 256.0
    assert int(state.scaler["hysteresis_left"]) == 1
    state, m = step(state, bad, rng)
    # Second overflow: scale halves, hysteresis re-arms.
    assert float(m["loss_scale"]) == 128.0
    assert int(state.scaler["hysteresis_left"]) == 2
    # Optimizer state was never touched by the skipped updates.
    opt_after = jax.tree_util.tree_leaves(state.opt_state)
    for a, b in zip(opt_before, opt_after):
        if hasattr(a, "shape") and a.dtype.kind == "f":
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_fp16_matches_fp32_step_when_no_overflow(rng):
    """At moderate scale with fp32 params, the scaled step equals the
    unscaled one (scaling is numerically transparent)."""
    model, s16 = _mk_state(fp16_scale=2.0 ** 6)
    _, s32 = _mk_state(fp16_scale=None)
    step16 = jax.jit(make_train_step(model, accum_steps=2))
    step32 = jax.jit(make_train_step(model, accum_steps=2))
    b = _batch(rng, accum=2)
    s16, m16 = step16(s16, b, rng)
    s32, m32 = step32(s32, b, rng)
    np.testing.assert_allclose(float(m16["loss"]), float(m32["loss"]), rtol=1e-6)
    a = jax.tree_util.tree_leaves(s16.trainable_and_frozen()[0])
    bb = jax.tree_util.tree_leaves(s32.trainable_and_frozen()[0])
    for x, y in zip(a, bb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-5,
                                   atol=1e-7)


# ----------------------------------------------------------------------
# ZeRO-3 param host offload
# ----------------------------------------------------------------------

def _offload_cfg(offload_params=True):
    return Config(
        model=CFG,
        lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=1),
        parallel=ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=4,
                                offload_params=offload_params,
                                offload_optimizer=True),
        data=DataConfig(max_seq_len=16),
        train=TrainConfig(micro_batch_size=4, grad_accum_steps=1),
        checkpoint=CheckpointConfig(save_strategy="no"),
    )


def test_param_offload_places_frozen_on_host(rng):
    cfg = _offload_cfg()
    mesh = build_mesh(cfg.parallel)
    model = LlamaForCausalLM(cfg.model, cfg.lora, mesh)
    tx = build_optimizer(cfg.optimizer)
    state = create_train_state(rng, model, tx, (4, 16), lora_enabled=True)
    state = shard_train_state(state, cfg, mesh)

    kernel = state.params["model"]["layers_0"]["attn"]["q_proj"]["kernel"]
    lora_a = state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_a"]
    assert kernel.sharding.memory_kind == "pinned_host"
    assert lora_a.sharding.memory_kind in (None, "device")


@pytest.mark.slow
def test_param_offload_step_matches_unoffloaded(rng):
    """One ZeRO-3 step with host-offloaded base params == same step with
    everything in device memory."""
    results = []
    for offload in (True, False):
        cfg = _offload_cfg(offload_params=offload)
        mesh = build_mesh(cfg.parallel)
        model = LlamaForCausalLM(cfg.model, cfg.lora, mesh)
        tx = build_optimizer(cfg.optimizer)
        state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                   lora_enabled=True)
        state = shard_train_state(state, cfg, mesh)
        step = make_sharded_train_step(model, state, cfg, mesh, accum_steps=2)
        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(1), (2, 4, 16), 0, cfg.model.vocab_size),
            "loss_mask": jnp.ones((2, 4, 16), jnp.int32),
        }
        state, m = step(state, batch, jax.random.PRNGKey(2))
        results.append((float(m["loss"]),
                        np.asarray(jax.device_get(
                            state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"]))))
    assert results[0][0] == pytest.approx(results[1][0], rel=1e-6)
    np.testing.assert_allclose(results[0][1], results[1][1], rtol=1e-5, atol=1e-7)


def test_param_offload_requires_lora():
    cfg = _offload_cfg()
    cfg = cfg.replace(lora=LoRAConfig(enabled=False))
    mesh = build_mesh(cfg.parallel)
    model = LlamaForCausalLM(cfg.model, None, mesh)
    tx = build_optimizer(cfg.optimizer)
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=False)
    with pytest.raises(ValueError, match="offload_params"):
        shard_train_state(state, cfg, mesh)


@pytest.mark.slow
def test_fp16_scaler_survives_checkpoint_resume(tmp_path, rng):
    """The dynamic scaler state checkpoints and restores with the rest of
    the train state."""
    from dlti_tpu.checkpoint import (latest_step, restore_train_state,
                                     save_train_state, wait_for_saves)

    model, state = _mk_state(fp16_scale=2.0 ** 8)
    step = jax.jit(make_train_step(model, accum_steps=1, fp16_scale_window=2))
    state, _ = step(state, _batch(rng), rng)
    state, _ = step(state, _batch(rng), rng)  # window hit: scale doubled
    assert float(state.scaler["scale"]) == 512.0

    save_train_state(str(tmp_path), 2, state, keep=2, async_save=False)
    wait_for_saves(str(tmp_path))

    _, fresh = _mk_state(fp16_scale=2.0 ** 8)
    assert latest_step(str(tmp_path)) == 2
    restored = restore_train_state(str(tmp_path), 2, fresh)
    assert float(restored.scaler["scale"]) == 512.0
    assert int(restored.scaler["good_steps"]) == int(state.scaler["good_steps"])
    # And training continues from the restored scaler.
    restored, m = step(restored, _batch(rng), rng)
    assert np.isfinite(float(m["loss"]))


def test_param_offload_streams_in_step_without_copies(rng):
    """Per-layer streaming contract (ds_config_zero3.json:19-27 analog):
    when the runtime supports host-memory compute operands, the frozen
    base params are operands of the compiled step — NOT step outputs and
    NOT boundary-copied. The same host buffers must flow through N steps
    unchanged (identity, not just equality), and they must stay in pinned
    host memory the whole time."""
    from dlti_tpu.parallel.sharding import _supports_host_compute_inputs
    from dlti_tpu.training.state import partition_params

    cfg = _offload_cfg()
    mesh = build_mesh(cfg.parallel)
    if not _supports_host_compute_inputs(mesh):
        pytest.skip("runtime lacks host-memory compute operands")
    model = LlamaForCausalLM(cfg.model, cfg.lora, mesh)
    tx = build_optimizer(cfg.optimizer)
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=True)
    state = shard_train_state(state, cfg, mesh)
    step = make_sharded_train_step(model, state, cfg, mesh, accum_steps=2)
    batch = {
        "input_ids": jax.random.randint(
            jax.random.PRNGKey(1), (2, 4, 16), 0, cfg.model.vocab_size),
        "loss_mask": jnp.ones((2, 4, 16), jnp.int32),
    }
    _, frozen0 = partition_params(state.params, True)
    for i in range(2):
        state, m = step(state, batch, jax.random.PRNGKey(2 + i))
    _, frozen2 = partition_params(state.params, True)
    assert frozen0 and frozen2.keys() == frozen0.keys()
    for k in frozen0:
        assert frozen2[k] is frozen0[k], f"frozen leaf {k} was copied"
        assert frozen2[k].sharding.memory_kind == "pinned_host", k
    # Trainable leaves did update and live on device.
    tr, _ = partition_params(state.params, True)
    assert all(v.sharding.memory_kind != "pinned_host" for v in tr.values())
    assert np.isfinite(float(m["loss"]))
