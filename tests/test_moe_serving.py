"""MoE (Mixtral-style) models through the serving engine (VERDICT r03 #9).

The engine needs no MoE-specific decode path by construction: MoEMLP is a
drop-in for LlamaMLP inside LlamaBlock (static top-k dispatch, fixed
expert capacity — all static shapes), and KV paging only touches
attention. These tests pin that: greedy engine decode == repeated dense
argmax forward, through prefill + block-table growth + continuous
batching, in fp32 and with int8-quantized expert weights.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

from dlti_tpu.config import MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving.engine import EngineConfig, InferenceEngine, SamplingParams

pytestmark = pytest.mark.slow

# moe_capacity_factor = E/k makes dispatch drop-free at ANY token count:
# with finite capacity a *full* forward drops overflow tokens as a function
# of sequence length, so incremental (cached) decode and the full-sequence
# forward legitimately diverge once a prompt overflows an expert — a
# property of GShard-style static capacity, not a caching bug. Drop-free
# config isolates the invariant these tests pin: KV-cache correctness.
CFG = dataclasses.replace(
    MODEL_PRESETS["mixtral_tiny"], dtype="float32", param_dtype="float32")
CFG = dataclasses.replace(
    CFG, moe_capacity_factor=float(CFG.num_experts) / CFG.num_experts_per_tok)


@pytest.fixture(scope="module")
def moe_model_and_params():
    model = LlamaForCausalLM(CFG)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def _dense_greedy(model, params, prompt, n_gen):
    toks = list(prompt)
    for _ in range(n_gen):
        logits, _ = model.apply({"params": params},
                                jnp.asarray([toks], jnp.int32),
                                deterministic=True)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


def test_moe_engine_greedy_matches_dense_forward(moe_model_and_params):
    model, params = moe_model_and_params
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]  # crosses a block boundary (bs=8)
    n_gen = 10
    expected = _dense_greedy(model, params, prompt, n_gen)

    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, params, ec)
    [res] = eng.generate([prompt], SamplingParams(temperature=0.0,
                                                  max_tokens=n_gen))
    assert res.output_token_ids == expected


def test_moe_engine_continuous_batching(moe_model_and_params):
    """Interleaved MoE requests share expert buffers correctly: each
    request's greedy output is independent of its batch company."""
    model, params = moe_model_and_params
    prompts = [[1, 2, 3], [7, 8, 9, 10, 11], [4, 5]]
    n_gen = 6
    expected = [_dense_greedy(model, params, p, n_gen) for p in prompts]

    ec = EngineConfig(max_seqs=3, block_size=8, num_blocks=32,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, params, ec)
    results = eng.generate(prompts, SamplingParams(temperature=0.0,
                                                   max_tokens=n_gen))
    for r, want in zip(results, expected):
        assert r.output_token_ids == want


def test_moe_engine_int8_weights_close_to_fp32(moe_model_and_params):
    """int8 weight-only quantization covers expert tensors (per-expert
    out-channel scales, MoEMLP's maybe_dequantize branch): the int8
    engine's greedy tokens track fp32 for most steps."""
    from dlti_tpu.models.quantization import quantize_params_int8

    model, params = moe_model_and_params
    prompt = [3, 1, 4, 1, 5, 9]
    n_gen = 8
    expected = _dense_greedy(model, params, prompt, n_gen)

    qparams = quantize_params_int8(params)
    w1 = qparams["model"]["layers_0"]["mlp"]["w1"]
    assert isinstance(w1, dict) and w1["q"].dtype == jnp.int8

    ec = EngineConfig(max_seqs=1, block_size=8, num_blocks=16,
                      max_model_len=32, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, qparams, ec)
    [res] = eng.generate([prompt], SamplingParams(temperature=0.0,
                                                  max_tokens=n_gen))
    agree = sum(a == b for a, b in zip(res.output_token_ids, expected))
    assert agree >= n_gen - 2, (res.output_token_ids, expected)
