"""Model-family widening: Qwen2 (qkv bias), Mistral (sliding window),
Gemma-style gelu MLP — logits parity with transformers + window semantics.

The reference loads models through HF Auto classes
(``training/train_baseline.py:122``), so sibling Llama-family checkpoints
are in its capability surface; these tests pin ours.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import ModelConfig
from dlti_tpu.models import LlamaForCausalLM, params_from_hf_state_dict
from dlti_tpu.ops.attention import reference_attention
from dlti_tpu.ops.pallas.flash_attention import flash_attention

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow


def _sd_numpy(model):
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}


def _assert_logits_match(our_cfg, hf_model, seq=16, tol=3e-4):
    torch = pytest.importorskip("torch")
    params = params_from_hf_state_dict(_sd_numpy(hf_model), our_cfg)
    ids = np.random.default_rng(0).integers(0, our_cfg.vocab_size, (2, seq))
    with torch.no_grad():
        want = hf_model(torch.tensor(ids)).logits.numpy()
    got, _ = LlamaForCausalLM(our_cfg).apply(
        {"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), want, rtol=tol, atol=tol)


def test_qwen2_logits_match_transformers():
    torch = pytest.importorskip("torch")
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False, rms_norm_eps=1e-6,
    )
    torch.manual_seed(0)
    hf_model = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=64, attention_bias=True,
        rms_norm_eps=1e-6, dtype="float32", param_dtype="float32", remat=False,
        attention_impl="reference",
    )
    _assert_logits_match(cfg, hf_model)


def test_mistral_sliding_window_logits_match_transformers():
    torch = pytest.importorskip("torch")
    from transformers import MistralConfig, MistralForCausalLM

    window = 6
    hf_cfg = MistralConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=window,
        tie_word_embeddings=False, attn_implementation="eager",
        rms_norm_eps=1e-6,
    )
    torch.manual_seed(0)
    hf_model = MistralForCausalLM(hf_cfg).eval()
    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=64, sliding_window=window,
        rms_norm_eps=1e-6, dtype="float32", param_dtype="float32", remat=False,
        attention_impl="reference",
    )
    _assert_logits_match(cfg, hf_model, seq=24)


def test_gelu_mlp_variant_runs():
    cfg = ModelConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64, num_layers=1,
        num_heads=2, num_kv_heads=2, max_seq_len=32, mlp_activation="gelu_tanh",
        dtype="float32", param_dtype="float32", remat=False,
        attention_impl="reference",
    )
    model = LlamaForCausalLM(cfg)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), ids)["params"]
    logits, _ = model.apply({"params": params}, ids)
    assert np.isfinite(np.asarray(logits)).all()


# ----------------------------------------------------------------------
# Sliding-window attention op semantics
# ----------------------------------------------------------------------

def _dense_window_attention(q, k, v, window):
    """O(s^2) masked softmax ground truth."""
    b, s, h, d = q.shape
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    qpos = np.arange(s)[:, None]
    kpos = np.arange(s)[None, :]
    allowed = (kpos <= qpos) & (kpos > qpos - window)
    scores = np.where(allowed[None, None], scores, -1e30)
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", probs, v)


def test_reference_attention_window():
    rng = np.random.default_rng(0)
    q = rng.standard_normal((2, 16, 2, 8)).astype(np.float32)
    k = rng.standard_normal((2, 16, 2, 8)).astype(np.float32)
    v = rng.standard_normal((2, 16, 2, 8)).astype(np.float32)
    got = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                              causal=True, window=5)
    want = _dense_window_attention(q, k, v, 5)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)


def test_flash_attention_window_matches_reference():
    rng = np.random.default_rng(1)
    s, w = 64, 20
    q = rng.standard_normal((1, s, 4, 32)).astype(np.float32)
    k = rng.standard_normal((1, s, 4, 32)).astype(np.float32)
    v = rng.standard_normal((1, s, 4, 32)).astype(np.float32)
    got = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True, window=w, block_q=16, block_kv=16,
                          interpret=True)
    want = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                               causal=True, window=w)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_flash_attention_window_gradients_match():
    rng = np.random.default_rng(2)
    s, w = 32, 9
    q = jnp.asarray(rng.standard_normal((1, s, 2, 16)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((1, s, 2, 16)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((1, s, 2, 16)), jnp.float32)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True, window=w, block_q=8,
                               block_kv=8, interpret=True).sum()

    def loss_ref(q, k, v):
        return reference_attention(q, k, v, causal=True, window=w).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_paged_decode_window_matches_reference():
    from dlti_tpu.ops.kv_cache import paged_gather
    from dlti_tpu.ops.pallas.paged_attention import paged_decode_attention

    rng = np.random.default_rng(3)
    batch, H, KVH, D, BS, NB, MB = 2, 4, 2, 32, 8, 16, 4
    seq_lens = np.array([13, 29], np.int32)
    window = 10
    k_pool = rng.standard_normal((NB, BS, KVH, D)).astype(np.float32)
    v_pool = rng.standard_normal((NB, BS, KVH, D)).astype(np.float32)
    perm = rng.permutation(NB)
    tables = np.full((batch, MB), -1, np.int32)
    nf = 0
    for b in range(batch):
        need = -(-seq_lens[b] // BS)
        tables[b, :need] = perm[nf:nf + need]
        nf += need
    q = rng.standard_normal((batch, 1, H, D)).astype(np.float32)

    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(tables), jnp.asarray(seq_lens), window=window,
        interpret=True)
    ck, cv = paged_gather({"k": jnp.asarray(k_pool), "v": jnp.asarray(v_pool)},
                          jnp.maximum(jnp.asarray(tables), 0))
    want = reference_attention(
        jnp.asarray(q), ck, cv, causal=True,
        q_positions=jnp.asarray(seq_lens)[:, None] - 1, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_gemma_logits_match_transformers():
    torch = pytest.importorskip("torch")
    from transformers import GemmaConfig, GemmaForCausalLM

    hf_cfg = GemmaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
        head_dim=16, max_position_embeddings=64, rms_norm_eps=1e-6,
        tie_word_embeddings=True, hidden_activation="gelu_pytorch_tanh",
    )
    torch.manual_seed(0)
    hf_model = GemmaForCausalLM(hf_cfg).eval()
    cfg = ModelConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128, num_layers=2,
        num_heads=4, num_kv_heads=4, head_dim=16, max_seq_len=64,
        rms_norm_eps=1e-6, tie_embeddings=True, mlp_activation="gelu_tanh",
        rmsnorm_offset=True, embedding_scale=True,
        dtype="float32", param_dtype="float32", remat=False,
        attention_impl="reference",
    )
    _assert_logits_match(cfg, hf_model, tol=1e-3)


def test_gemma_config_from_hf():
    from dlti_tpu.models import config_from_hf

    cfg = config_from_hf({
        "model_type": "gemma", "vocab_size": 256, "hidden_size": 64,
        "intermediate_size": 128, "num_hidden_layers": 2,
        "num_attention_heads": 4, "num_key_value_heads": 4, "head_dim": 16,
        "rms_norm_eps": 1e-6, "hidden_activation": "gelu_pytorch_tanh",
    })
    assert cfg.rmsnorm_offset and cfg.embedding_scale and cfg.tie_embeddings
    assert cfg.mlp_activation == "gelu_tanh"
