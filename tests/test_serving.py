"""Serving engine tests: paged KV cache, sampling, continuous batching.

The reference has no serving code (SURVEY.md §0) so there is nothing to
mirror; these tests pin the contracts our engine defines:

* paged-cache decode == contiguous-cache decode == full-context forward
* sampling: greedy==argmax, top-k/top-p masking, determinism
* continuous batching: interleaved admission, preemption, block accounting
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dlti_tpu.config import MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.ops.kv_cache import init_paged_cache, paged_gather, paged_update, slot_mapping
from dlti_tpu.serving import (
    BlockManager, EngineConfig, InferenceEngine, SamplingParams,
)
from dlti_tpu.serving.sampling import sample_tokens

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow

CFG = MODEL_PRESETS["llama_tiny"]


@pytest.fixture(scope="module")
def tiny_model_and_params():
    model = LlamaForCausalLM(CFG, None)
    rng = jax.random.PRNGKey(0)
    ids = jnp.zeros((1, 8), jnp.int32)
    params = model.init(rng, ids)["params"]
    return model, params


# ----------------------------------------------------------------------
# Paged cache ops
# ----------------------------------------------------------------------

def test_slot_mapping_and_update_roundtrip():
    bs, nb, kvh, hd = 4, 8, 2, 4
    cache = init_paged_cache(1, nb, bs, kvh, hd, jnp.float32)[0]
    # One sequence using physical blocks [3, 5]; write 6 tokens.
    bt = jnp.array([[3, 5]], jnp.int32)
    pos = jnp.arange(6, dtype=jnp.int32)[None, :]
    k = jnp.arange(6 * kvh * hd, dtype=jnp.float32).reshape(1, 6, kvh, hd)
    slots = slot_mapping(bt, pos, bs, nb)
    np.testing.assert_array_equal(
        np.asarray(slots)[0], [3 * bs + 0, 3 * bs + 1, 3 * bs + 2, 3 * bs + 3,
                               5 * bs + 0, 5 * bs + 1])
    cache = paged_update(cache, k, k, slots)
    gk, _ = paged_gather(cache, bt)
    np.testing.assert_allclose(np.asarray(gk[0, :6]), np.asarray(k[0]))


def test_padding_positions_are_dropped():
    bs, nb, kvh, hd = 4, 4, 1, 2
    cache = init_paged_cache(1, nb, bs, kvh, hd, jnp.float32)[0]
    bt = jnp.array([[1]], jnp.int32)
    pos = jnp.array([[0, -1]], jnp.int32)  # second token is padding
    k = jnp.ones((1, 2, kvh, hd), jnp.float32)
    slots = slot_mapping(bt, pos, bs, nb)
    cache = paged_update(cache, k, k, slots)
    # Only slot (1, 0) written; nothing else (especially not block 0).
    got = np.asarray(cache["k"])
    assert got[1, 0].sum() == kvh * hd
    assert got.sum() == kvh * hd


def test_paged_decode_matches_full_forward(tiny_model_and_params):
    """Prefill+decode through the paged cache == one full dense forward."""
    model, params = tiny_model_and_params
    rng = jax.random.PRNGKey(1)
    n_prompt, n_total = 5, 9
    tokens = jax.random.randint(rng, (1, n_total), 0, CFG.vocab_size)

    # Dense forward over the whole sequence (no cache).
    full_logits, _ = model.apply({"params": params}, tokens, deterministic=True)

    # Paged: prefill the prompt, then decode token by token.
    bs, nb = 4, 8
    cache = init_paged_cache(CFG.num_layers, nb, bs, CFG.num_kv_heads,
                             CFG.resolved_head_dim, jnp.float32)
    blocks = [2, 5, 7]  # enough for 9 tokens at block_size 4
    bt = jnp.zeros((1, 3), jnp.int32).at[0, :3].set(jnp.array(blocks))

    def run(cache, ids, pos):
        layer_caches = [{**c, "block_tables": bt} for c in cache]
        logits, new = model.apply({"params": params}, ids, positions=pos,
                                  cache=layer_caches, deterministic=True)
        return logits, [{"k": c["k"], "v": c["v"]} for c in new]

    pos = jnp.arange(n_prompt, dtype=jnp.int32)[None, :]
    logits, cache = run(cache, tokens[:, :n_prompt], pos)
    np.testing.assert_allclose(np.asarray(logits[0, n_prompt - 1]),
                               np.asarray(full_logits[0, n_prompt - 1]),
                               rtol=2e-4, atol=2e-4)
    for t in range(n_prompt, n_total):
        pos = jnp.array([[t]], jnp.int32)
        logits, cache = run(cache, tokens[:, t:t + 1], pos)
        if t < n_total - 1:
            np.testing.assert_allclose(np.asarray(logits[0, 0]),
                                       np.asarray(full_logits[0, t]),
                                       rtol=2e-4, atol=2e-4)


# ----------------------------------------------------------------------
# Sampling
# ----------------------------------------------------------------------

def test_greedy_is_argmax():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (3, 50))
    toks, lps = sample_tokens(
        logits, rng, jnp.zeros((3,)), jnp.zeros((3,), jnp.int32), jnp.ones((3,)))
    np.testing.assert_array_equal(np.asarray(toks), np.argmax(np.asarray(logits), -1))
    # Reported logprob is log softmax at the chosen token.
    expect = jax.nn.log_softmax(logits, -1)[jnp.arange(3), toks]
    np.testing.assert_allclose(np.asarray(lps), np.asarray(expect), rtol=1e-5)


def test_top_k_one_is_greedy():
    rng = jax.random.PRNGKey(3)
    logits = jax.random.normal(rng, (4, 32)) * 3
    toks, _ = sample_tokens(
        logits, rng, jnp.ones((4,)), jnp.ones((4,), jnp.int32), jnp.ones((4,)))
    np.testing.assert_array_equal(np.asarray(toks), np.argmax(np.asarray(logits), -1))


def test_top_k_restricts_support():
    rng = jax.random.PRNGKey(4)
    logits = jnp.asarray(np.random.RandomState(0).randn(1, 100) * 2)
    top5 = set(np.argsort(-np.asarray(logits[0]))[:5].tolist())
    for i in range(20):
        toks, _ = sample_tokens(
            logits, jax.random.fold_in(rng, i), jnp.ones((1,)),
            jnp.array([5], jnp.int32), jnp.ones((1,)))
        assert int(toks[0]) in top5


def test_top_p_keeps_head_token():
    # top_p smaller than the head prob must still sample the head token.
    logits = jnp.array([[10.0, 0.0, 0.0, 0.0]])
    toks, _ = sample_tokens(
        logits, jax.random.PRNGKey(0), jnp.ones((1,)),
        jnp.zeros((1,), jnp.int32), jnp.array([1e-6]))
    assert int(toks[0]) == 0


def test_sampling_deterministic_given_key():
    rng = jax.random.PRNGKey(7)
    logits = jax.random.normal(rng, (2, 64))
    a, _ = sample_tokens(logits, rng, jnp.ones((2,)), jnp.zeros((2,), jnp.int32),
                         jnp.array([0.9, 0.9]))
    b, _ = sample_tokens(logits, rng, jnp.ones((2,)), jnp.zeros((2,), jnp.int32),
                         jnp.array([0.9, 0.9]))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------------------
# Block manager
# ----------------------------------------------------------------------

def test_block_manager_allocation_contract(monkeypatch):
    monkeypatch.setenv("DLTI_DISABLE_NATIVE", "1")
    bm = BlockManager(num_blocks=8, block_size=4)
    assert bm.num_free == 7  # block 0 reserved
    a = bm.allocate(3)
    assert a is not None and len(set(a)) == 3 and 0 not in a
    assert bm.allocate(5) is None  # all-or-nothing
    assert bm.num_free == 4
    bm.free(a)
    assert bm.num_free == 7
    assert bm.blocks_needed(1) == 1 and bm.blocks_needed(4) == 1
    assert bm.blocks_needed(5) == 2


# ----------------------------------------------------------------------
# Engine: continuous batching end-to-end (tiny model, CPU)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def engine(tiny_model_and_params):
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64, max_model_len=64,
                      cache_dtype="float32", eos_token_id=-1)  # no natural EOS
    return InferenceEngine(CFG, params, ec)


def test_engine_batch_generation(engine):
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8], [9], [10, 11]]
    results = engine.generate(prompts, SamplingParams(temperature=0.0, max_tokens=6))
    assert len(results) == 4
    for r in results:
        assert len(r.output_token_ids) == 6
        assert r.finish_reason == "length"
        assert all(0 <= t < CFG.vocab_size for t in r.output_token_ids)
    # All blocks returned to the pool afterwards.
    assert engine.block_manager.num_free == engine.cfg.num_blocks - 1
    assert engine.num_active == 0


def test_engine_greedy_matches_uncached_forward(engine, tiny_model_and_params):
    """Engine greedy decode == repeated dense argmax forward (the strongest
    correctness check: exercises prefill, paging, block growth, sampling)."""
    model, params = tiny_model_and_params
    prompt = [3, 1, 4, 1, 5, 9, 2, 6]  # crosses a block boundary (bs=8)
    n_gen = 10

    toks = list(prompt)
    for _ in range(n_gen):
        logits, _ = model.apply({"params": params},
                                jnp.asarray([toks], jnp.int32), deterministic=True)
        toks.append(int(jnp.argmax(logits[0, -1])))
    expected = toks[len(prompt):]

    [res] = engine.generate([prompt], SamplingParams(temperature=0.0,
                                                     max_tokens=n_gen))
    assert res.output_token_ids == expected


def test_engine_interleaved_submission(engine):
    """Requests arriving mid-flight join the running decode batch."""
    r1 = engine.submit([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=8))
    for _ in range(3):
        engine.step()
    r2 = engine.submit([4, 5], SamplingParams(temperature=0.0, max_tokens=4))
    while engine.has_work:
        engine.step()
    assert r1.done and r2.done
    assert len(r1.output_token_ids) == 8
    assert len(r2.output_token_ids) == 4


def test_engine_more_requests_than_slots(engine):
    prompts = [[i + 1] for i in range(10)]  # > max_seqs=4
    results = engine.generate(prompts, SamplingParams(temperature=0.0, max_tokens=3))
    assert all(len(r.output_token_ids) == 3 for r in results)


def test_engine_preemption_under_memory_pressure(tiny_model_and_params):
    model, params = tiny_model_and_params
    # Pool of 7 usable blocks * 8 tokens; 3 long-running seqs must contend.
    ec = EngineConfig(max_seqs=3, block_size=8, num_blocks=8, max_model_len=48,
                      cache_dtype="float32", eos_token_id=-1)
    eng = InferenceEngine(CFG, params, ec)
    prompts = [[1, 2, 3, 4, 5, 6, 7], [8, 9, 10, 11, 12, 13], [14, 15, 16, 17, 18]]
    results = eng.generate(prompts, SamplingParams(temperature=0.0, max_tokens=12))
    assert all(len(r.output_token_ids) == 12 for r in results)
    assert eng.stats["preemptions"] >= 1
    assert eng.block_manager.num_free == ec.num_blocks - 1


def test_engine_rejects_unsatisfiable_pool(tiny_model_and_params):
    """A pool that can never hold one max-length sequence would livelock
    the FCFS head of _admit() forever — must fail at construction."""
    _, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=8, max_model_len=64,
                      cache_dtype="float32")
    with pytest.raises(ValueError, match="num_blocks"):
        InferenceEngine(CFG, params, ec)


def test_engine_rejects_empty_prompt(engine):
    with pytest.raises(ValueError):
        engine.submit([])


def test_engine_per_request_seed_reproducible(engine):
    """A seeded request's sample stream is independent of batch company."""
    p = SamplingParams(temperature=1.0, max_tokens=5, seed=123)
    [alone] = engine.generate([[1, 2, 3]], p)
    # Same request again, now sharing the batch with other traffic.
    seeded = engine.submit([1, 2, 3], p)
    engine.submit([9, 8, 7], SamplingParams(temperature=1.0, max_tokens=7))
    engine.submit([4, 4], SamplingParams(temperature=0.7, max_tokens=3))
    while engine.has_work:
        engine.step()
    assert seeded.output_token_ids == alone.output_token_ids


def test_engine_stop_tokens(engine, tiny_model_and_params):
    """Generation halts at a stop token with finish_reason='stop'."""
    model, params = tiny_model_and_params
    prompt = [7, 7, 7]
    # Find what greedy emits first, then declare it a stop token.
    logits, _ = model.apply({"params": params}, jnp.asarray([prompt], jnp.int32),
                            deterministic=True)
    first = int(jnp.argmax(logits[0, -1]))
    [res] = engine.generate([prompt], SamplingParams(
        temperature=0.0, max_tokens=10, stop_token_ids=(first,)))
    assert res.output_token_ids == [first]
    assert res.finish_reason == "stop"


def test_engine_decode_with_pallas_kernel_matches_gather(tiny_model_and_params):
    """Forcing the Pallas paged-decode kernel (interpreted on CPU) produces
    the same greedy tokens as the XLA gather path."""
    import dataclasses

    model, params = tiny_model_and_params
    cfg_kernel = dataclasses.replace(CFG, paged_attention_impl="kernel")
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32, max_model_len=48,
                      cache_dtype="float32", eos_token_id=-1)
    prompts = [[3, 1, 4, 1, 5, 9], [2, 7, 1, 8, 2, 8, 1, 8, 2]]
    sp = SamplingParams(temperature=0.0, max_tokens=5)

    want = InferenceEngine(CFG, params, ec).generate(prompts, sp)
    got = InferenceEngine(cfg_kernel, params, ec).generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids


def test_engine_tensor_parallel_matches_single_device(tiny_model_and_params):
    """TP=2 engine (params + KV pools sharded over 'tensor') produces the
    same greedy tokens as the unsharded engine."""
    from dlti_tpu.config import ParallelConfig
    from dlti_tpu.parallel import build_mesh

    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32, max_model_len=48,
                      cache_dtype="float32", eos_token_id=-1)
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8]]
    sp = SamplingParams(temperature=0.0, max_tokens=5)

    want = InferenceEngine(CFG, params, ec).generate(prompts, sp)

    mesh = build_mesh(ParallelConfig(tensor=2), devices=jax.devices()[:2])
    tp_engine = InferenceEngine(CFG, params, ec, mesh=mesh)
    # Weights and pools really are sharded.
    k0 = tp_engine.cache[0]["k"]
    assert k0.sharding.spec[2] == "tensor"
    got = tp_engine.generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids


def test_engine_tp_mesh_validation(tiny_model_and_params):
    from dlti_tpu.config import ParallelConfig
    from dlti_tpu.parallel import build_mesh

    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32, max_model_len=48,
                      cache_dtype="float32", eos_token_id=-1)
    with pytest.raises(ValueError, match="tensor"):
        InferenceEngine(CFG, params, ec,
                        mesh=build_mesh(ParallelConfig(data=2, tensor=2),
                                        devices=jax.devices()[:4]))


def test_multi_step_decode_matches_single_step(tiny_model_and_params):
    """steps_per_sync=4 produces identical tokens (greedy AND seeded
    sampling) to single-step decode, including mid-window EOS handling."""
    model, params = tiny_model_and_params

    def mk(steps):
        ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                          max_model_len=64, cache_dtype="float32",
                          eos_token_id=-1, steps_per_sync=steps)
        return InferenceEngine(CFG, params, ec)

    prompts = [[3, 1, 4, 1, 5, 9], [2, 7, 1, 8, 2, 8]]
    for sp in (SamplingParams(temperature=0.0, max_tokens=11),
               SamplingParams(temperature=0.8, top_k=20, seed=7, max_tokens=11)):
        want = mk(1).generate(prompts, sp)
        got = mk(4).generate(prompts, sp)
        for g, w in zip(got, want):
            assert g.output_token_ids == w.output_token_ids
            assert g.finish_reason == w.finish_reason


def test_warmup_ladder_aot_dispatch_matches_cold(tiny_model_and_params):
    """warmup_decode_ladder pre-compiles the decode ladder AND keeps the
    AOT executables on the dispatch path (r04 advisor: lower().compile()
    results were discarded, so with the persistent cache disabled the
    warmup silently did nothing). Tokens must match a cold engine, and
    the AOT path must still be live afterwards (no silent fallback)."""
    model, params = tiny_model_and_params

    def mk(steps):
        ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                          max_model_len=64, cache_dtype="float32",
                          eos_token_id=-1, steps_per_sync=steps)
        return InferenceEngine(CFG, params, ec)

    prompts = [[3, 1, 4, 1, 5, 9], [2, 7, 1, 8]]
    sp = SamplingParams(temperature=0.0, max_tokens=9)
    want = mk(4).generate(prompts, sp)

    warm = mk(4)
    warm.warmup_decode_ladder()
    warm.warmup_decode_ladder()  # idempotent: re-warm must not crash
    assert hasattr(warm._decode_fn, "_aot_state")
    got = warm.generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids
    # Every ladder program dispatched through its compiled executable.
    assert warm._decode_fn._aot_state["aot"]
    for k, fn in warm._multi_decode_fns.items():
        assert getattr(fn, "_aot_state", {"aot": True})["aot"], k


def test_multi_step_decode_respects_stop_tokens(tiny_model_and_params):
    """A stop token hit mid-window finishes the request there; later
    window tokens are discarded."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=1, block_size=8, num_blocks=32,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1, steps_per_sync=4)
    engine = InferenceEngine(CFG, params, ec)
    # Find what greedy generates, then stop on its 2nd token.
    [probe] = engine.generate([[5, 4, 3]], SamplingParams(temperature=0.0,
                                                          max_tokens=8))
    stop_tok = probe.output_token_ids[1]
    [r] = engine.generate([[5, 4, 3]], SamplingParams(
        temperature=0.0, max_tokens=8, stop_token_ids=(stop_tok,)))
    assert r.output_token_ids[-1] == stop_tok
    assert len(r.output_token_ids) == 2
    assert r.finish_reason == "stop"
    assert engine.num_active == 0


def test_speculative_ngram_matches_plain_greedy(tiny_model_and_params):
    """n-gram speculative decoding emits exactly the plain greedy tokens,
    with nonzero acceptance on repetitive prompts."""
    model, params = tiny_model_and_params

    def mk(spec):
        ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                          max_model_len=96, cache_dtype="float32",
                          eos_token_id=-1,
                          speculative="ngram" if spec else "none",
                          num_draft_tokens=4, ngram_size=2)
        return InferenceEngine(CFG, params, ec)

    # Repetitive prompts so the trailing n-gram has earlier matches.
    prompts = [[7, 8, 9, 7, 8, 9, 7, 8], [4, 5, 4, 5, 4, 5, 4]]
    sp = SamplingParams(temperature=0.0, max_tokens=16)
    want = mk(False).generate(prompts, sp)
    spec_engine = mk(True)
    got = spec_engine.generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids
        np.testing.assert_allclose(g.output_logprobs, w.output_logprobs,
                                   atol=1e-4)
    assert spec_engine.stats["spec_proposed"] > 0
    # Greedy continuations of repeated patterns should accept sometimes;
    # fewer model calls than tokens proves multi-token emission.
    total_tokens = sum(len(r.output_token_ids) for r in got)
    assert spec_engine.stats["decode_steps"] < total_tokens


def test_speculative_mixed_batch_per_slot_gating(tiny_model_and_params):
    """Per-slot gating: a greedy slot speculates while a sampling slot in
    the SAME batch takes its exact single-step draw — one sampling request
    no longer disables speculation batch-wide, and both requests emit
    exactly what the plain engine emits."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1, speculative="ngram")
    engine = InferenceEngine(CFG, params, ec)
    r1 = engine.submit([7, 8, 9, 7, 8, 9], SamplingParams(temperature=0.0,
                                                          max_tokens=8))
    r2 = engine.submit([1, 2, 3], SamplingParams(temperature=0.9, seed=3,
                                                 max_tokens=8))
    while engine.has_work:
        engine.step()
    assert len(r1.output_token_ids) == 8 and len(r2.output_token_ids) == 8
    # The greedy slot really did speculate despite the sampling neighbor.
    assert engine.stats["spec_proposed"] > 0

    plain = InferenceEngine(CFG, params, EngineConfig(
        max_seqs=2, block_size=8, num_blocks=64, max_model_len=64,
        cache_dtype="float32", eos_token_id=-1))
    p1 = plain.submit([7, 8, 9, 7, 8, 9], SamplingParams(temperature=0.0,
                                                         max_tokens=8))
    p2 = plain.submit([1, 2, 3], SamplingParams(temperature=0.9, seed=3,
                                                max_tokens=8))
    while plain.has_work:
        plain.step()
    assert r1.output_token_ids == p1.output_token_ids
    assert r2.output_token_ids == p2.output_token_ids


def test_speculative_composes_with_multi_step(tiny_model_and_params):
    """speculative="ngram" + steps_per_sync=4 chains 4 propose→verify
    rounds in ONE compiled program: emissions match plain greedy exactly
    and the host syncs far less than once per token."""
    model, params = tiny_model_and_params

    def mk(spec, steps):
        return InferenceEngine(CFG, params, EngineConfig(
            max_seqs=2, block_size=8, num_blocks=128, max_model_len=192,
            cache_dtype="float32", eos_token_id=-1,
            speculative="ngram" if spec else "none",
            steps_per_sync=steps, num_draft_tokens=4, ngram_size=2))

    prompts = [[7, 8, 9, 7, 8, 9, 7, 8], [4, 5, 4, 5, 4, 5, 4]]
    sp = SamplingParams(temperature=0.0, max_tokens=24)
    want = mk(False, 1).generate(prompts, sp)
    eng = mk(True, 4)
    got = eng.generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids
        np.testing.assert_allclose(g.output_logprobs, w.output_logprobs,
                                   atol=1e-4)
    assert eng.stats["spec_accepted"] > 0
    # 4 rounds/sync and multi-token acceptance: model calls well under
    # one per emitted token.
    total = sum(len(r.output_token_ids) for r in got)
    assert eng.stats["decode_steps"] < total


def test_speculative_adaptive_gate_stays_exact(tiny_model_and_params):
    """With an unreachably high acceptance threshold the gate pauses
    proposing (plain multi-step rounds) and periodically re-probes —
    outputs stay exactly greedy throughout."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=128,
                      max_model_len=192, cache_dtype="float32",
                      eos_token_id=-1, speculative="ngram",
                      steps_per_sync=2, spec_min_acceptance=100.0,
                      spec_probe_window=2, spec_cooldown=3)
    prompts = [[7, 8, 9, 7, 8, 9, 7, 8], [4, 5, 4, 5, 4, 5, 4]]
    sp = SamplingParams(temperature=0.0, max_tokens=24)
    eng = InferenceEngine(CFG, params, ec)
    got = eng.generate(prompts, sp)
    plain = InferenceEngine(CFG, params, EngineConfig(
        max_seqs=2, block_size=8, num_blocks=128, max_model_len=192,
        cache_dtype="float32", eos_token_id=-1))
    want = plain.generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids
    # The gate must have actually paused at least once (tracked stat).
    assert eng.stats["spec_paused_rounds"] > 0


# ----------------------------------------------------------------------
# Replicated (data-parallel) serving
# ----------------------------------------------------------------------

def test_replicated_engine_matches_single_engine(tiny_model_and_params):
    """2 replicas x TP=2: same greedy tokens as one unsharded engine, with
    requests actually spread across both replicas."""
    from dlti_tpu.serving import ReplicatedEngine

    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32, max_model_len=48,
                      cache_dtype="float32", eos_token_id=-1)
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8], [5, 5, 5],
               [9, 8, 7, 6, 5]]
    sp = SamplingParams(temperature=0.0, max_tokens=5)

    want = InferenceEngine(CFG, params, ec).generate(prompts, sp)

    rep = ReplicatedEngine(CFG, params, ec, replicas=2, tensor=2,
                           devices=jax.devices()[:4])
    got = rep.generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids

    stats = rep.stats
    per_replica = [r["requests"] for r in stats["replicas"]]
    assert stats["requests"] == len(prompts)
    assert all(n > 0 for n in per_replica), per_replica


def test_replicated_engine_single_chip_replicas(tiny_model_and_params):
    """tensor=1 replicas pin weights to distinct devices."""
    from dlti_tpu.serving import ReplicatedEngine

    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32, max_model_len=48,
                      cache_dtype="float32", eos_token_id=-1)
    rep = ReplicatedEngine(CFG, params, ec, replicas=2, tensor=1,
                           devices=jax.devices()[:2])
    devs = [next(iter(jax.tree_util.tree_leaves(e.params)[0].devices()))
            for e in rep.engines]
    assert devs[0] != devs[1]
    out = rep.generate([[1, 2, 3], [4, 5, 6]],
                       SamplingParams(temperature=0.0, max_tokens=4))
    assert all(len(r.output_token_ids) == 4 for r in out)


def test_replicated_engine_rejects_overcommit(tiny_model_and_params):
    from dlti_tpu.serving import ReplicatedEngine

    model, params = tiny_model_and_params
    with pytest.raises(ValueError, match="devices"):
        ReplicatedEngine(CFG, params, EngineConfig(max_seqs=2, block_size=8,
                                                   num_blocks=32,
                                                   max_model_len=48),
                         replicas=5, tensor=2)


def test_engine_commits_host_params_to_device(tiny_model_and_params):
    """Checkpoint restores hand back host (numpy) arrays; the engine must
    pin them to its device once at construction — otherwise every compiled
    call re-uploads the whole tree (measured ~40 s/step for a 300M model
    over the remote relay)."""
    model, params = tiny_model_and_params
    host_params = jax.tree_util.tree_map(np.asarray, params)
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=48, cache_dtype="float32", eos_token_id=-1)
    eng = InferenceEngine(CFG, host_params, ec)
    leaves = jax.tree_util.tree_leaves(eng.params)
    assert all(isinstance(v, jax.Array) for v in leaves)
    dev = jax.devices()[0]
    assert all(next(iter(v.devices())) == dev for v in leaves)
    out = eng.generate([[1, 2, 3]], SamplingParams(temperature=0.0, max_tokens=3))
    assert len(out[0].output_token_ids) == 3


def test_batched_admission_matches_sequential(tiny_model_and_params):
    """Admitting N requests in one step (one batched prefill call per
    bucket) must produce the same greedy tokens as admitting them one at
    a time (stepping between submissions)."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64, max_model_len=48,
                      cache_dtype="float32", eos_token_id=-1)
    prompts = [[3, 1, 4, 1, 5], [2, 7, 1, 8, 2, 8], [9, 9, 8], [1, 2, 3, 4]]
    sp = SamplingParams(temperature=0.0, max_tokens=6)

    batched = InferenceEngine(CFG, params, ec).generate(prompts, sp)

    seq_engine = InferenceEngine(CFG, params, ec)
    reqs = []
    for p in prompts:  # force one-at-a-time admission
        reqs.append(seq_engine.submit(p, sp))
        seq_engine.step()
    while seq_engine.has_work:
        seq_engine.step()
    for b, r in zip(batched, reqs):
        assert b.output_token_ids == r.output_token_ids


# ----------------------------------------------------------------------
# Chunked prefill (latency mode)
# ----------------------------------------------------------------------

def test_chunked_prefill_matches_unchunked(tiny_model_and_params):
    """With max_prefill_tokens_per_step set, prompts prefill across several
    engine steps — and every request's greedy output must be identical to
    throughput mode (same KV content, same first-token logits)."""
    model, params = tiny_model_and_params
    mk = lambda chunk: EngineConfig(
        max_seqs=4, block_size=8, num_blocks=64, max_model_len=64,
        cache_dtype="float32", eos_token_id=-1,
        max_prefill_tokens_per_step=chunk)
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8, 9, 7],
               [2, 7, 1, 8, 2, 8, 1, 8, 2, 8],
               [9, 9, 8, 2, 6],
               [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]]
    sp = SamplingParams(temperature=0.0, max_tokens=6)
    want = InferenceEngine(CFG, params, mk(0)).generate(prompts, sp)
    for chunk in (4, 8, 16):
        got = InferenceEngine(CFG, params, mk(chunk)).generate(prompts, sp)
        for w, g in zip(want, got):
            assert g.output_token_ids == w.output_token_ids, f"chunk={chunk}"


def test_chunked_prefill_decode_runs_alongside(tiny_model_and_params):
    """A long prompt prefilling in chunks must not stall a running decode:
    the active slot keeps emitting one token per engine step."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1, max_prefill_tokens_per_step=4)
    eng = InferenceEngine(CFG, params, ec)
    sp = SamplingParams(temperature=0.0, max_tokens=20)
    r1 = eng.submit([5, 3, 1], sp)
    eng.step()  # r1 prefilled (3 <= 4) and decoding
    n0 = len(r1.output_token_ids)
    assert n0 >= 1
    # 16-token prompt at 4 tokens/step: 4 steps of chunked prefill.
    r2 = eng.submit(list(range(1, 17)), sp)
    for i in range(4):
        before = len(r1.output_token_ids)
        eng.step()
        assert len(r1.output_token_ids) == before + 1, (
            f"decode stalled during prefill chunk {i}")
    assert len(r2.output_token_ids) >= 1  # r2's first token landed
    while eng.has_work:
        eng.step()
    # r2's output equals the dense greedy reference (its KV is uncorrupted
    # by the interleaved decodes).
    toks = list(range(1, 17))
    for _ in range(len(r2.output_token_ids)):
        logits, _ = model.apply({"params": params},
                                jnp.asarray([toks], jnp.int32),
                                deterministic=True)
        toks.append(int(jnp.argmax(logits[0, -1])))
    assert r2.output_token_ids == toks[16:]


def test_chunked_prefill_with_prefix_cache(tiny_model_and_params):
    """Chunked prefill composes with automatic prefix caching: the cached
    prefix is skipped and only the suffix chunks through."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1, max_prefill_tokens_per_step=4,
                      enable_prefix_caching=True)
    eng = InferenceEngine(CFG, params, ec)
    sp = SamplingParams(temperature=0.0, max_tokens=5)
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
    [first] = eng.generate([prompt], sp)
    [second] = eng.generate([prompt], sp)
    assert second.output_token_ids == first.output_token_ids
    assert eng.stats["prefix_cached_tokens"] > 0


def test_chunked_prefill_preemption_mid_prefill(tiny_model_and_params):
    """Preempting a slot mid-prefill requeues it cleanly (recompute on
    readmit; nothing half-written is trusted).

    Construction: A (older) decodes and grows its blocks; B (younger)
    chunk-prefills a long prompt at 1 token/step. The pool is sized so
    A's growth exhausts it while B is still prefilling — the youngest-
    victim preemption must hit B mid-prefill."""
    model, params = tiny_model_and_params
    # 11 usable blocks of 4 tokens. A: 2 at admission, grows while
    # decoding 24 tokens (7 by the end). B: reserves 7 for its 24-token
    # prompt. 2 + 7 = 9 leaves 2 for A's growth -> exhaustion ~8 decode
    # steps in, while B (1 token/step) is ~1/3 prefilled.
    ec = EngineConfig(max_seqs=2, block_size=4, num_blocks=12,
                      max_model_len=40, cache_dtype="float32",
                      eos_token_id=-1, max_prefill_tokens_per_step=1)
    eng = InferenceEngine(CFG, params, ec)
    a = eng.submit([1, 2, 3, 4], SamplingParams(temperature=0.0,
                                                max_tokens=24))
    b = eng.submit(list(range(1, 25)), SamplingParams(temperature=0.0,
                                                      max_tokens=4))
    preempted_while_prefilling = False
    while eng.has_work:
        eng.step()
        if b.num_preemptions and not b.output_token_ids:
            # B was evicted before producing any token => mid-prefill.
            preempted_while_prefilling = True
    assert preempted_while_prefilling, (
        "scenario failed to preempt B mid-prefill; re-tune pool sizing")
    assert len(a.output_token_ids) == 24
    # B recomputed from scratch after readmission and still matches the
    # dense greedy reference.
    toks = list(range(1, 25))
    for _ in range(len(b.output_token_ids)):
        logits, _ = model.apply({"params": params},
                                jnp.asarray([toks], jnp.int32),
                                deterministic=True)
        toks.append(int(jnp.argmax(logits[0, -1])))
    assert b.output_token_ids == toks[24:]
    assert eng.block_manager.num_free == ec.num_blocks - 1


def test_decode_slot_occupancy_stat(tiny_model_and_params):
    """decode_slot_steps tracks active-slot x step units, bounding mean
    occupancy: generated <= slot_steps <= max_seqs * decode_steps."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64,
                      max_model_len=48, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, params, ec)
    eng.generate([[3, 1, 4], [1, 5, 9, 2], [6, 5]],
                 SamplingParams(temperature=0.0, max_tokens=6))
    st = eng.stats
    assert st["decode_slot_steps"] > 0
    assert st["decode_slot_steps"] <= ec.max_seqs * st["decode_steps"]
    assert st["generated_tokens"] <= st["decode_slot_steps"] + len(
        eng.finished)  # +1 prefill-sampled token per request


def test_budget_clamped_window_full_occupancy(tiny_model_and_params):
    """The r03 occupancy lever: with uniform max_tokens, multi-step windows
    clamp to the smallest remaining budget (halving ladder), so no slot
    ever idles inside a window — 100% decode-slot occupancy — and the
    emitted tokens are identical to the unclamped/single-step stream."""
    model, params = tiny_model_and_params
    prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5], [8, 9, 7]]

    def run(sync):
        ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64,
                          max_model_len=48, cache_dtype="float32",
                          eos_token_id=-1, steps_per_sync=sync)
        eng = InferenceEngine(CFG, params, ec)
        res = eng.generate(prompts,
                           SamplingParams(temperature=0.0, max_tokens=10))
        return eng, [r.output_token_ids for r in res]

    eng, toks = run(sync=8)
    ref_eng, ref_toks = run(sync=1)
    assert toks == ref_toks, "clamped windows changed the token stream"

    st = eng.stats
    # All 4 slots admitted together with budget 9 after the prefill token:
    # windows 8 then 1 (ladder), zero dead slot-steps -> 100% occupancy.
    assert st["decode_slot_steps"] == 4 * st["decode_steps"], st


def test_window_never_exceeds_kv_room_near_model_len(tiny_model_and_params):
    """Round-up windows must round back DOWN under hard KV room: a slot
    near max_model_len with a large max_tokens budget must finish with a
    length stop, not overflow its block table (regression: round-up clamp
    picked k past max_blocks_per_seq)."""
    model, params = tiny_model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=32, cache_dtype="float32",
                      eos_token_id=-1, steps_per_sync=8)
    eng = InferenceEngine(CFG, params, ec)
    prompt = list(range(1, 27))  # 26 tokens, 6 from the model-length stop
    [res] = eng.generate([prompt], SamplingParams(temperature=0.0,
                                                  max_tokens=100))
    assert res.finish_reason == "length"
    assert len(prompt) + len(res.output_token_ids) <= ec.max_model_len


def test_mixed_budget_windows_identical_stream(tiny_model_and_params):
    """A short-budget request joining a long cohort shrinks the shared
    window while it lives (round-up ladder) and the engine returns to
    full windows after it retires — with a token stream identical to
    single-step decode."""
    model, params = tiny_model_and_params
    prompts = [[3, 1, 4], [1, 5, 9, 2], [6, 5]]
    budgets = [40, 6, 40]

    def run(sync):
        ec = EngineConfig(max_seqs=3, block_size=8, num_blocks=64,
                          max_model_len=64, cache_dtype="float32",
                          eos_token_id=-1, steps_per_sync=sync)
        eng = InferenceEngine(CFG, params, ec)
        reqs = [eng.submit(p, SamplingParams(temperature=0.0, max_tokens=b))
                for p, b in zip(prompts, budgets)]
        while eng.has_work:
            eng.step()
        return eng, [r.output_token_ids for r in reqs]

    eng, toks = run(sync=16)
    _, ref_toks = run(sync=1)
    assert toks == ref_toks
    assert [len(t) for t in toks] == budgets
    st = eng.stats
    # Windows shrank for the short slot then recovered: strictly fewer
    # rounds than single-step decode would need.
    assert st["decode_steps"] < sum(budgets)
    # Zero wasted LIVE slot-steps: every counted slot-step produced a
    # token (prefill supplies each request's first token). Mean occupancy
    # vs max_seqs is NOT asserted — this workload drains with no waiting
    # queue, so slots legitimately sit empty at the tail.
    assert st["decode_slot_steps"] == sum(budgets) - len(prompts), st
