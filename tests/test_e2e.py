"""End-to-end flows: train→checkpoint→resume→export, packing, trainer loop.

This is the canonical user flow (see .claude/skills/verify/SKILL.md) pinned
as a test: the reference's notebook-driven manual matrix (train.ipynb),
automated.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig,
    Config,
    DataConfig,
    LoRAConfig,
    MODEL_PRESETS,
    OptimizerConfig,
    ParallelConfig,
    TrainConfig,
    ZeROStage,
)
from dlti_tpu.data import ByteTokenizer, format_conversation_for_llama2, make_batches
from dlti_tpu.training.trainer import Trainer

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow


def _cfg(tmp_path, **train_kwargs):
    defaults = dict(num_epochs=1, micro_batch_size=8, grad_accum_steps=2,
                    logging_steps=100, max_steps=8,
                    # never append to the repo's committed metrics CSV
                    metrics_csv=str(tmp_path / "metrics.csv"))
    defaults.update(train_kwargs)
    return Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(zero_stage=ZeROStage.ZERO2, data=8),
        data=DataConfig(max_seq_len=64, tokenizer="byte"),
        checkpoint=CheckpointConfig(
            output_dir=str(tmp_path / "ckpt"), save_steps=4,
            save_total_limit=2, async_save=False,
        ),
        train=TrainConfig(**defaults),
    )


def _texts(n=300):
    return [
        format_conversation_for_llama2(
            {"question": f"What is {i} + {i}?", "answer": f"It is {2 * i}."}
        )["text"]
        for i in range(n)
    ]


def _dataset(cfg, pack=False):
    return make_batches(
        _texts(), ByteTokenizer(), seq_len=cfg.data.max_seq_len,
        micro_batch_size=cfg.train.micro_batch_size,
        grad_accum_steps=cfg.train.grad_accum_steps,
        shard_by_host=False, pack=pack,
    )


def test_train_checkpoint_resume_export(tmp_path):
    cfg = _cfg(tmp_path)
    ds = _dataset(cfg)
    state, record = Trainer(cfg).train(dataset=ds)
    assert np.isfinite(record.final_loss)
    assert record.experiment == "zero2_8dev"

    from dlti_tpu.checkpoint import latest_step, list_checkpoint_steps

    assert latest_step(cfg.checkpoint.output_dir) == 8
    assert list_checkpoint_steps(cfg.checkpoint.output_dir) == [4, 8]  # keep-2

    # Resume continues to max_steps without retraining consumed batches.
    cfg2 = _cfg(tmp_path, max_steps=12)
    state2, _ = Trainer(cfg2).train(dataset=_dataset(cfg2))
    assert int(jax.device_get(state2.step)) == 12

    # Export merged model and run a forward.
    from dlti_tpu.checkpoint import export_merged_model, load_exported_model
    from dlti_tpu.models import LlamaForCausalLM

    export_merged_model(str(tmp_path / "export"), state2.params, cfg2)
    params, ecfg = load_exported_model(str(tmp_path / "export"))
    assert not ecfg.lora.enabled
    logits, _ = LlamaForCausalLM(ecfg.model).apply(
        {"params": params}, jnp.arange(8, dtype=jnp.int32)[None, :]
    )
    assert logits.shape[-1] == ecfg.model.vocab_size


def test_packed_training_runs_and_masks_boundaries(tmp_path):
    cfg = _cfg(tmp_path, max_steps=3)
    cfg = cfg.replace(checkpoint=CheckpointConfig(
        output_dir=str(tmp_path / "ckpt2"), save_strategy="no"))
    # Short docs (~15 tokens) so several pack into each 64-token row.
    texts = [f"q{i}? a{2 * i}." for i in range(600)]
    ds = make_batches(
        texts, ByteTokenizer(), seq_len=cfg.data.max_seq_len,
        micro_batch_size=cfg.train.micro_batch_size,
        grad_accum_steps=cfg.train.grad_accum_steps,
        shard_by_host=False, pack=True,
    )
    batch = next(ds.epoch(0))
    assert set(batch) == {"input_ids", "loss_mask", "segment_ids", "positions"}
    segs = batch["segment_ids"].reshape(-1, cfg.data.max_seq_len)
    mask = batch["loss_mask"].reshape(-1, cfg.data.max_seq_len)
    pos = batch["positions"].reshape(-1, cfg.data.max_seq_len)
    # Rows contain >1 document (packing actually packs these short samples).
    assert segs.max() > 1
    # Boundary targets are masked: wherever seg changes, mask == 0.
    changes = segs[:, 1:] != segs[:, :-1]
    assert np.all(mask[:, 1:][changes] == 0)
    # Positions restart at document starts.
    doc_starts = (segs[:, 1:] != segs[:, :-1]) & (segs[:, 1:] > 0)
    assert np.all(pos[:, 1:][doc_starts] == 0)

    state, record = Trainer(cfg).train(dataset=ds)
    assert np.isfinite(record.final_loss)


def test_multihost_sharding_math(monkeypatch):
    """Per-host views agree on steps_per_epoch (ragged splits would deadlock
    collectives on the last step), each batch carries the host's 1/N
    batch-column slice of the global microbatch, and the GLOBAL schedule
    (which rows feed which optimizer step) is world-size invariant — the
    contract elastic mesh reshape relies on."""
    from dlti_tpu.data import pipeline as pl_mod

    tok = ByteTokenizer()
    # Distinguishable rows: row j starts with token j.
    seqs = [[j % 250 + 1, 2, 3] for j in range(101)]

    def view(pid, procs, mbs=4, accum=1):
        monkeypatch.setattr(jax, "process_count", lambda: procs)
        monkeypatch.setattr(jax, "process_index", lambda: pid)
        return pl_mod.TokenBatchDataset(
            seqs, 8, tok.pad_id, micro_batch_size=mbs,
            grad_accum_steps=accum, shard_by_host=True)

    views = [view(pid, 4) for pid in range(4)]
    steps = {v.steps_per_epoch() for v in views}
    assert len(steps) == 1 and steps.pop() == 25  # 101 // 4 global rows
    batch = next(views[0].epoch(0))
    assert batch["input_ids"].shape == (1, 1, 8)  # 4 global / 4 hosts = 1

    # Reassembling the four host slices along the batch dim reproduces the
    # single-host global batch exactly, step for step.
    single = view(0, 1)
    for step_idx, (g, *locals_) in enumerate(zip(
            single.epoch(0), *[v.epoch(0) for v in views])):
        stacked = np.concatenate([b["input_ids"] for b in locals_], axis=1)
        np.testing.assert_array_equal(stacked, g["input_ids"])
        if step_idx >= 3:
            break

    # World-size invariance incl. grad-accum rescale (2 hosts x bs2 vs
    # 1 host x bs4, and 1 host with rows moved into the accum dim): the
    # same global rows feed the same optimizer step.
    two = [view(pid, 2) for pid in range(2)]
    for g, a, b in zip(single.epoch(0), two[0].epoch(0), two[1].epoch(0)):
        np.testing.assert_array_equal(
            np.concatenate([a["input_ids"], b["input_ids"]], axis=1),
            g["input_ids"])
        break
    reshaped = view(0, 1, mbs=2, accum=2)  # rescale_batch_schedule(4,1,2,1)
    g0 = next(single.epoch(0))["input_ids"].reshape(-1, 8)
    r0 = next(reshaped.epoch(0))["input_ids"].reshape(-1, 8)
    np.testing.assert_array_equal(g0, r0)


def test_global_bs_not_divisible_by_procs_raises(monkeypatch):
    from dlti_tpu.data import pipeline as pl_mod

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    with pytest.raises(ValueError, match="divisible"):
        pl_mod.TokenBatchDataset([[1, 2]] * 8, 8, 0, micro_batch_size=3,
                                 grad_accum_steps=1, shard_by_host=True)


def test_bad_micro_batch_for_mesh_raises(tmp_path):
    cfg = _cfg(tmp_path, micro_batch_size=4)  # mesh data=8 -> 4 % 8 != 0
    ds = _dataset(cfg)
    with pytest.raises(ValueError, match="divisible"):
        Trainer(cfg).train(dataset=ds)
