"""Goodput ledger & critical-path attribution tests (tier-1 + one slow
drill).

The two conservation contracts this subsystem makes:

* **Training**: every wall-clock second of a run is booked to exactly one
  bucket — the bucket totals of an instrumented CPU run sum to the
  measured wall clock within 1% (by construction: a phase clock, not a
  collection of timers that can overlap or leak).
* **Serving**: a request's phase breakdown (gateway queue → engine queue
  → tier restore → prefill → failover/preempt → decode) sums to its
  client-observed latency.

Plus the satellites: steplog per-phase fields, the watchdog's
goodput_collapse rule, the elastic stitching (restart downtime +
shrunk-world degradation), ``GET /debug/slow``, and the response-level
phase objects loadgen decomposes cold-vs-warm TTFT with.
"""

import http.client
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    TelemetryConfig, TrainConfig, WatchdogConfig,
)
from dlti_tpu.telemetry import GoodputLedger, request_breakdown
from dlti_tpu.telemetry.ledger import (
    CriticalPathTracker, GOODPUT_BUCKETS, PRODUCTIVE_BUCKETS,
    REQUEST_PHASES, SlowLog, stitch_ledgers,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CFG = MODEL_PRESETS["llama_tiny"]


# ----------------------------------------------------------------------
# The phase clock
# ----------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t

    def tick(self, dt):
        self.t += dt


def test_phase_clock_conservation_synthetic():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    clk.tick(2.0)                     # startup
    led.enter("step_compute")
    clk.tick(1.0)
    led.enter("device_sync")
    clk.tick(0.5)
    led.enter("data_wait")
    clk.tick(0.25)
    led.enter("other")
    clk.tick(0.25)                    # open phase, still counted
    t = led.totals()
    assert t["startup"] == pytest.approx(2.0)
    assert t["step_compute"] == pytest.approx(1.0)
    assert t["device_sync"] == pytest.approx(0.5)
    assert t["data_wait"] == pytest.approx(0.25)
    assert t["other"] == pytest.approx(0.25)
    assert sum(t.values()) == pytest.approx(led.wall())
    assert led.goodput_fraction() == pytest.approx(1.5 / 4.0)
    # Deltas drain once and re-accrue.
    d = led.take_deltas()
    assert d["startup"] == pytest.approx(2.0)
    assert led.take_deltas() == {}
    s = led.scalars()
    assert s["goodput_fraction"] == pytest.approx(1.5 / 4.0)
    assert s["goodput_wall_seconds"] == pytest.approx(4.0)


def test_disabled_ledger_is_inert():
    led = GoodputLedger(enabled=False)
    led.enter("step_compute")
    led.begin_replay(5)
    assert led.replay_until is None      # begin_replay no-ops disabled
    assert led.totals() == {}
    assert led.take_deltas() == {}
    assert led.scalars() == {}
    assert led.wall() == 0.0
    assert led.goodput_fraction() == 0.0
    assert led.save("/nonexistent/x.json") is None


def test_replay_reclassifies_step_buckets():
    clk = FakeClock()
    led = GoodputLedger(clock=clk)
    led.enter("step_compute")
    clk.tick(1.0)
    led.enter("other")                # fresh progress: step_compute
    led.begin_replay(until_step=7)
    led.enter("step_compute")
    clk.tick(2.0)
    led.enter("device_sync")
    clk.tick(0.5)
    led.enter("other")                # both step buckets -> replay
    led.end_replay()
    led.enter("step_compute")
    clk.tick(1.0)
    led.enter("other")                # fresh again
    t = led.totals()
    assert t["replay"] == pytest.approx(2.5)
    assert t["step_compute"] == pytest.approx(2.0)
    assert sum(t.values()) == pytest.approx(led.wall())


def test_bucket_catalog_is_schema_stable():
    # The steplog/postmortem parse bucket names; REQUEST_PHASES labels
    # the /metrics phase counter.
    assert set(PRODUCTIVE_BUCKETS) <= set(GOODPUT_BUCKETS)
    for b in GOODPUT_BUCKETS + REQUEST_PHASES:
        assert b == b.lower().replace("-", "_")


# ----------------------------------------------------------------------
# Acceptance: instrumented CPU training run — conservation within 1%
# ----------------------------------------------------------------------

def test_trainer_books_every_second(tmp_path):
    from dlti_tpu.training import Trainer

    cfg = Config(
        model=CFG,
        lora=LoRAConfig(enabled=False),
        data=DataConfig(max_seq_len=16),
        checkpoint=CheckpointConfig(save_strategy="no"),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, max_steps=3, logging_steps=1),
        telemetry=TelemetryConfig(
            step_log_path=str(tmp_path / "steps.jsonl")),
    )
    rng = np.random.default_rng(0)
    ids = [rng.integers(1, 500, (1, 2, 16), dtype=np.int32)
           for _ in range(3)]
    batches = [{"input_ids": a, "labels": a} for a in ids]
    trainer = Trainer(cfg)
    t0 = time.monotonic()
    trainer.train(batches_per_epoch=batches)
    wall = time.monotonic() - t0
    led = trainer._ledger
    assert led.enabled
    totals = led.totals()
    booked = sum(totals.values())
    # Conservation: bucket totals == the ledger's own wall within 1%
    # (they're equal by construction; the tolerance covers clock reads),
    # and the ledger's wall covers the train() call's measured wall.
    assert booked == pytest.approx(led.wall(), rel=0.01)
    assert led.wall() <= wall + 0.05
    assert led.wall() >= 0.9 * wall - 0.05
    for bucket in ("startup", "step_compute", "device_sync", "data_wait"):
        assert bucket in totals, totals
    for bucket in totals:
        assert bucket in GOODPUT_BUCKETS, bucket
    assert 0.0 < led.goodput_fraction() <= 1.0
    # Steplog per-phase fields rode along (schema satellite).
    recs = [json.loads(l) for l in open(tmp_path / "steps.jsonl")]
    steps = [r for r in recs if r["type"] == "step"]
    assert len(steps) == 3
    for r in steps:
        for key in ("data_wait_s", "sync_s", "ckpt_s", "rollback_s"):
            assert key in r and r[key] >= 0.0
    assert sum(r["sync_s"] for r in steps) > 0.0
    # The /debug/vars scalar feed carries the ledger series.
    s = led.scalars()
    assert "goodput_fraction" in s and "goodput_step_compute_seconds" in s


def test_trainer_ledger_disabled_books_nothing(tmp_path):
    from dlti_tpu.training import Trainer

    cfg = Config(
        model=CFG,
        lora=LoRAConfig(enabled=False),
        data=DataConfig(max_seq_len=16),
        checkpoint=CheckpointConfig(save_strategy="no"),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, max_steps=1, logging_steps=1),
        telemetry=TelemetryConfig(
            goodput_ledger=False,
            step_log_path=str(tmp_path / "steps.jsonl")),
    )
    rng = np.random.default_rng(0)
    a = rng.integers(1, 500, (1, 2, 16), dtype=np.int32)
    trainer = Trainer(cfg)
    trainer.train(batches_per_epoch=[{"input_ids": a, "labels": a}])
    assert not trainer._ledger.enabled
    assert trainer._ledger.totals() == {}
    recs = [json.loads(l) for l in open(tmp_path / "steps.jsonl")]
    step = next(r for r in recs if r["type"] == "step")
    assert step["data_wait_s"] == 0.0 and step["sync_s"] == 0.0


# ----------------------------------------------------------------------
# Serving: request breakdown conservation
# ----------------------------------------------------------------------

def _fake_request(**kw):
    from dlti_tpu.serving.engine import Request

    req = Request(request_id="r1", prompt_token_ids=[1, 2, 3])
    for k, v in kw.items():
        setattr(req, k, v)
    return req


def test_request_breakdown_sums_exactly():
    t0 = 1000.0
    req = _fake_request(
        gateway_enqueue_time=t0,
        arrival_time=t0 + 0.10,       # 0.10 gateway queue
        admitted_time=t0 + 0.25,      # 0.15 engine queue
        restore_s=0.05,               # tier restore inside admission
        first_token_time=t0 + 0.60,   # prefill = 0.35 - 0.05 restore
        finish_time=t0 + 1.60,        # decode 1.0
    )
    b = request_breakdown(req)
    p = b["phases"]
    assert b["total_s"] == pytest.approx(1.60)
    assert b["ttft_s"] == pytest.approx(0.60)
    assert p["gateway_queue"] == pytest.approx(0.10)
    assert p["queue"] == pytest.approx(0.15)
    assert p["tier_restore"] == pytest.approx(0.05)
    assert p["prefill"] == pytest.approx(0.30)
    assert p["decode"] == pytest.approx(1.0)
    assert sum(p.values()) == pytest.approx(b["total_s"], abs=1e-9)
    assert set(p) <= set(REQUEST_PHASES)
    events = [name for name, _ in b["timeline"]]
    assert events == ["gateway_enqueue", "submitted", "admitted",
                      "first_token", "finish"]


def test_request_breakdown_books_failover_and_preempt_stalls():
    t0 = 2000.0
    req = _fake_request(
        arrival_time=t0,
        admitted_time=t0 + 0.1,
        first_token_time=t0 + 0.5,
        finish_time=t0 + 2.0,
        stall_s={"failover": 0.4, "preempt": 0.2},
        stall_prefill_s=0.3,          # 0.3 of the stall was pre-first-token
    )
    b = request_breakdown(req)
    p = b["phases"]
    assert p["failover"] == pytest.approx(0.4)
    assert p["preempt"] == pytest.approx(0.2)
    # prefill = (0.5-0.1) - 0.3 pre-token stall; decode = 1.5 - 0.3 rest.
    assert p["prefill"] == pytest.approx(0.1)
    assert p["decode"] == pytest.approx(1.2)
    assert sum(p.values()) == pytest.approx(b["total_s"], abs=1e-9)


def test_note_requeue_readmit_roundtrip():
    from dlti_tpu.telemetry.ledger import note_readmitted, note_requeue

    req = _fake_request(arrival_time=time.monotonic())
    note_requeue(req, "failover")
    time.sleep(0.02)
    note_readmitted(req)
    assert req.stall_s["failover"] >= 0.02
    assert req.stall_prefill_s == pytest.approx(
        req.stall_s["failover"])      # no first token yet -> pre side
    note_readmitted(req)              # idempotent without an open mark
    assert len(req.stall_s) == 1


def test_slowlog_keeps_k_worst():
    log = SlowLog(k=3)
    for i, total in enumerate([0.5, 2.0, 0.1, 3.0, 1.0]):
        log.add({"id": f"r{i}", "total_s": total})
    worst = log.worst()
    assert [e["total_s"] for e in worst] == [3.0, 2.0, 1.0]
    assert len(log) == 3
    assert [e["total_s"] for e in log.worst(1)] == [3.0]


def test_tracker_observes_once_per_request():
    from dlti_tpu.telemetry.ledger import phase_requests_total

    tr = CriticalPathTracker(slow_k=4)
    req = _fake_request(arrival_time=time.monotonic() - 0.5,
                        finish_time=time.monotonic())
    before = phase_requests_total.value
    assert tr.observe(req) is not None
    assert tr.observe(req) is None     # double finish dedups
    assert phase_requests_total.value == before + 1
    tr.enabled = False
    req2 = _fake_request(arrival_time=time.monotonic() - 0.5,
                         finish_time=time.monotonic())
    assert tr.observe(req2) is None


@pytest.fixture(scope="module")
def tiny_engine():
    import jax
    import jax.numpy as jnp

    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine

    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1)
    return InferenceEngine(CFG, params, ec)


def test_engine_breakdown_sums_to_observed_latency(tiny_engine):
    from dlti_tpu.serving import SamplingParams

    results = tiny_engine.generate(
        [[1, 2, 3, 4], [5, 6, 7]],
        SamplingParams(max_tokens=4, temperature=0.0))
    by_id = {r.request_id: r for r in results}
    seen = 0
    for req in tiny_engine.finished:
        if req.request_id not in by_id:
            continue
        seen += 1
        b = request_breakdown(req)
        lat = by_id[req.request_id].latency_s
        # The acceptance tolerance: breakdown sums to the request's
        # observed latency within 1% (both derive from the same clocks;
        # the residual "other" keeps the sum exact).
        assert sum(b["phases"].values()) == pytest.approx(b["total_s"],
                                                          abs=1e-6)
        assert b["total_s"] == pytest.approx(lat, rel=0.01, abs=0.002)
    assert seen == 2
    # The shared tracker retained them with phases attached.
    worst = tiny_engine.telemetry.critical_path.slow.worst()
    assert len(worst) >= 2
    assert all("prefill" in e["phases"] for e in worst[:2])


# ----------------------------------------------------------------------
# Live server: /debug/slow + response phase objects (client-observed
# conservation)
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def phase_server(tiny_engine):
    from dlti_tpu.data.tokenizer import ByteTokenizer
    from dlti_tpu.serving import SamplingParams
    from dlti_tpu.serving.server import ServerConfig, make_server

    httpd, aeng = make_server(
        tiny_engine, ByteTokenizer(),
        ServerConfig(host="127.0.0.1", port=0,
                     default_params=SamplingParams(max_tokens=4)))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield "127.0.0.1", port
    httpd.shutdown()
    aeng.shutdown()
    httpd.sampler.stop()
    httpd.server_close()


def _post_json(host, port, path, body, timeout=120):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data)


def _get_json(host, port, path, timeout=60):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, json.loads(data)


def test_served_request_phases_sum_to_client_latency(phase_server):
    host, port = phase_server
    t0 = time.monotonic()
    st, body = _post_json(host, port, "/v1/completions",
                          {"prompt": "hello", "max_tokens": 4,
                           "temperature": 0.0})
    client_latency = time.monotonic() - t0
    assert st == 200
    phases = body.get("phases")
    assert phases, body.keys()
    parts = {k: v for k, v in phases.items()
             if k not in ("total_s", "ttft_s")}
    # Conservation: the phase parts sum to the server-observed total
    # exactly, and that total is within tolerance of what the client
    # measured (HTTP framing + tokenize ride outside the engine clock).
    assert sum(parts.values()) == pytest.approx(phases["total_s"],
                                                abs=1e-6)
    assert phases["total_s"] <= client_latency + 0.001
    assert phases["total_s"] >= client_latency - 0.25
    assert set(parts) <= set(REQUEST_PHASES)


def test_debug_slow_retains_worst_with_timelines(phase_server):
    host, port = phase_server
    _post_json(host, port, "/v1/completions",
               {"prompt": "again", "max_tokens": 3, "temperature": 0.0})
    st, obj = _get_json(host, port, "/debug/slow")
    assert st == 200
    assert obj["k"] >= 1 and obj["retained"] >= 1
    assert obj["phases"] == list(REQUEST_PHASES)
    worst = obj["worst"]
    assert worst == sorted(worst, key=lambda e: -e["total_s"])
    for e in worst:
        assert sum(e["phases"].values()) == pytest.approx(e["total_s"],
                                                          abs=1e-6)
        assert e["timeline"][0][0] in ("submitted", "gateway_enqueue")
        assert e["timeline"][-1][0] == "finish"
    st, obj = _get_json(host, port, "/debug/slow?n=1")
    assert st == 200 and len(obj["worst"]) == 1


def test_debug_slow_rejects_bad_n(phase_server):
    host, port = phase_server
    st, _ = _get_json(host, port, "/debug/slow?n=zebra")
    assert st == 400


# ----------------------------------------------------------------------
# Watchdog: goodput_collapse rule
# ----------------------------------------------------------------------

def test_watchdog_goodput_collapse_rule():
    from dlti_tpu.telemetry import AnomalyWatchdog, TimeSeriesSampler

    cell = {"goodput_fraction": 0.9}
    sampler = TimeSeriesSampler(interval_s=60.0)
    sampler.add_source(lambda: dict(cell))
    cfg = WatchdogConfig(enabled=True, goodput_floor_frac=0.5,
                         goodput_min_samples=6)
    wd = AnomalyWatchdog(cfg, sampler)
    for _ in range(8):
        sampler.sample_now()
    assert [a for a in wd.check_now() if a["rule"] == "goodput_collapse"] \
        == []
    cell["goodput_fraction"] = 0.2   # < 0.5 x median(0.9)
    sampler.sample_now()
    fired = [a for a in wd.check_now() if a["rule"] == "goodput_collapse"]
    assert len(fired) == 1
    assert "goodput" in fired[0]["message"]
    # Edge-triggered: still collapsed -> no duplicate alert.
    sampler.sample_now()
    assert [a for a in wd.check_now()
            if a["rule"] == "goodput_collapse"] == []
    # Recovery re-arms, a second collapse fires again.
    cell["goodput_fraction"] = 0.85
    for _ in range(3):
        sampler.sample_now()
    wd.check_now()
    cell["goodput_fraction"] = 0.1
    sampler.sample_now()
    assert [a for a in wd.check_now()
            if a["rule"] == "goodput_collapse"]


# ----------------------------------------------------------------------
# Elastic stitching
# ----------------------------------------------------------------------

def test_stitch_ledgers_books_downtime_and_shrink():
    workers = [
        {"generation": 0, "rank": 0,
         "buckets": {"step_compute": 8.0, "device_sync": 2.0,
                     "rollback": 1.0, "replay": 1.5}, "wall_s": 12.5},
        {"generation": 0, "rank": 1,   # peer rank: must NOT double-count
         "buckets": {"step_compute": 8.0, "device_sync": 2.0},
         "wall_s": 10.0},
        {"generation": 1, "rank": 0,
         "buckets": {"step_compute": 4.0, "checkpoint_restore": 1.0},
         "wall_s": 5.0},
    ]
    timeline = [
        {"generation": 0, "world_size": 2, "start": 0.0, "end": 13.0,
         "outcome": "failure"},
        {"generation": 1, "world_size": 1, "start": 15.0, "end": 21.0,
         "outcome": "done"},
    ]
    st = stitch_ledgers(workers, timeline, num_slots=2)
    assert st["restart_downtime_s"] == pytest.approx(2.0)
    assert st["shrunk_world_s"] == pytest.approx(6.0)
    assert st["shrunk_world_capacity_loss_s"] == pytest.approx(3.0)
    b = st["buckets"]
    assert b["step_compute"] == pytest.approx(12.0)   # 8 + 4, not 16+4
    assert b["replay"] == pytest.approx(1.5)
    assert b["rollback"] == pytest.approx(1.0)
    assert b["restart_downtime"] == pytest.approx(2.0)
    assert st["wall_s"] == pytest.approx(sum(b.values()))
    assert 0 < st["goodput_fraction"] < 1
    assert st["num_generations"] == 2


def test_generation_ledger_file_roundtrip(tmp_path, monkeypatch):
    from dlti_tpu.telemetry.ledger import load_generation_ledgers
    from dlti_tpu.training import elastic

    monkeypatch.setenv(elastic.ENV_ELASTIC_DIR, str(tmp_path))
    monkeypatch.setenv(elastic.ENV_GENERATION, "2")
    monkeypatch.setenv("DLTI_PROCESS_ID", "1")
    led = GoodputLedger()
    led.enter("step_compute")
    time.sleep(0.01)
    led.enter("other")
    path = elastic.save_generation_ledger(led.to_dict(), step=7, force=True)
    assert path and os.path.basename(path) == "ledger_g2_r1.json"
    loaded = load_generation_ledgers(str(tmp_path))
    assert len(loaded) == 1
    assert loaded[0]["generation"] == 2 and loaded[0]["rank"] == 1
    assert loaded[0]["step"] == 7
    assert loaded[0]["buckets"]["step_compute"] > 0


# ----------------------------------------------------------------------
# Slow drill: elastic host-kill + sentinel rollback -> stitched ledger
# books restart downtime, shrunk-world, and replay; postmortem renders
# "where the time went" from the flight dumps + stitched ledger.
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_goodput_drill_hostkill_plus_rollback_stitched(tmp_path):
    n_rows, seq = 128, 32
    data = tmp_path / "data.txt"
    data.write_text("".join(
        f"row {i:04d} " + "x" * 64 + "\n" for i in range(n_rows)))

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_DEFAULT_MATMUL_PRECISION"] = "highest"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # Supervisor-side whole-host chaos (workers ignore host-kill; their
    # own injector runs the CLI nan-grad spec below).
    env["DLTI_TRAIN_FAULT_INJECT"] = "5:host-kill"

    ckpt = tmp_path / "ckpt"
    flight = tmp_path / "flight"
    elastic_dir = tmp_path / "elastic"
    steplog = tmp_path / "steps.jsonl"
    cmd = [
        sys.executable, os.path.join(REPO, "scripts", "train.py"),
        "--preset", "zero3", "--model", "llama_tiny",
        "--tokenizer", "byte", "--dataset-path", str(data),
        "--output-dir", str(ckpt), "--max-seq-len", str(seq),
        "--per-device-batch-size", "1",
        "--gradient-accumulation-steps", "2",
        "--num-train-epochs", "1", "--save-steps", "2",
        "--save-total-limit", "10", "--warmup-steps", "2",
        "--logging-steps", "1", "--prefetch-depth", "0",
        "--step-log", str(steplog),
        "--metrics-csv", str(tmp_path / "m.csv"),
        # In-process numeric chaos: NaN grads at step 3 -> one-anomaly
        # rollback to the step-2 checkpoint -> replay.
        "--fault-inject-step", "3:nan-grad",
        "--sentinel-rollback-after", "1",
        "--flight-dir", str(flight),
    ]
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "launch.py"),
         "--num-processes", "2", "--elastic",
         "--restart-budget", "4", "--backoff", "0.5",
         "--ckpt-dir", str(ckpt), "--elastic-dir", str(elastic_dir),
         "--log-dir", str(tmp_path / "logs"), "--term-grace", "30", "--",
         *cmd],
        env=env, capture_output=True, text=True, timeout=900, cwd=REPO)
    logs = ""
    logdir = tmp_path / "logs"
    if logdir.is_dir():
        for p in sorted(logdir.iterdir()):
            if p.suffix == ".err":
                logs += f"--- {p.name} ---\n" + p.read_text()[-1500:]
    assert proc.returncode == 0, (
        f"supervisor rc={proc.returncode}\n{proc.stderr[-2000:]}\n{logs}")

    # The stitched ledger books what no single worker can see.
    stitched_path = elastic_dir / "ledger_stitched.json"
    assert stitched_path.is_file(), os.listdir(elastic_dir)
    st = json.loads(stitched_path.read_text())
    assert st["num_slots"] == 2
    assert st["restart_downtime_s"] > 0, st
    assert st["shrunk_world_s"] > 0, st          # the world-1 generation
    assert st["shrunk_world_capacity_loss_s"] > 0
    b = st["buckets"]
    assert b.get("restart_downtime", 0) > 0
    assert b.get("replay", 0) > 0, b             # rolled-back steps re-run
    assert b.get("rollback", 0) > 0, b           # the restore itself
    assert b.get("step_compute", 0) > 0
    assert 0 < st["goodput_fraction"] < 1

    # Steplog recorded the rollback in its per-phase fields too.
    recs = [json.loads(l) for l in open(steplog)]
    assert any(r.get("rollback_s", 0) > 0 for r in recs
               if r.get("type") == "step")

    # postmortem --all renders one incident with "where the time went"
    # (stitched across generations, auto-discovering the elastic dir
    # next to the flight dir).
    pm = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         str(flight), "--all", "--ledger", str(stitched_path)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert pm.returncode == 0, pm.stderr[-1500:]
    assert "where the time went (stitched across generations)" \
        in pm.stdout, pm.stdout[-2000:]
    assert "restart downtime" in pm.stdout
    # And the machine-readable form carries the stitched ledger verbatim.
    pmj = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         str(flight), "--all", "--json", "--ledger", str(stitched_path)],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=120)
    assert pmj.returncode == 0, pmj.stderr[-1500:]
    incident = json.loads(pmj.stdout)
    assert incident["stitched_ledger"]["buckets"].get("replay", 0) > 0


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q", "-m", "not slow"]))
