"""Disk-backed streaming token store vs the in-memory dataset.

The reference's data plane is Arrow-memory-mapped (``datasets``
``save_to_disk``/``load_from_disk``, ``prepare_dataset.py:92``); the
streaming store is our corpus-scale equivalent. Contract under test: the
memmap dataset yields byte-identical batches to ``TokenBatchDataset`` for
the same corpus and seed, with only O(rows) host memory, and a train step
runs straight off the memmaps.
"""

import json
import os

import numpy as np
import pytest

from dlti_tpu.data.pipeline import TokenBatchDataset
from dlti_tpu.data.streaming import StreamingTokenDataset, write_token_store


def _docs(n=64, seed=0, lo=3, hi=40):
    gen = np.random.default_rng(seed)
    return [list(map(int, gen.integers(1, 250, size=int(gen.integers(lo, hi)))))
            for _ in range(n)]


@pytest.mark.parametrize("pack", [False, True], ids=["padded", "packed"])
def test_streaming_matches_in_memory(tmp_path, pack):
    docs = _docs()
    seq_len, pad_id = 32, 0
    store = str(tmp_path / "store")
    # chunk_docs small so the writer really streams in several chunks.
    write_token_store(iter(docs), store, seq_len=seq_len, pad_id=pad_id,
                      pack=pack, chunk_docs=1000)

    mem = TokenBatchDataset(sequences=docs, seq_len=seq_len, pad_id=pad_id,
                            micro_batch_size=4, grad_accum_steps=2,
                            shuffle_seed=7, shard_by_host=False, pack=pack)
    disk = StreamingTokenDataset(store, micro_batch_size=4,
                                 grad_accum_steps=2, shuffle_seed=7,
                                 shard_by_host=False)
    # Packed row construction differs only in doc->row assignment when the
    # in-memory packer pre-shuffles; compare against the unshuffled packing
    # order by building the in-memory dataset without a packing shuffle.
    if pack:
        mem = TokenBatchDataset(sequences=docs, seq_len=seq_len,
                                pad_id=pad_id, micro_batch_size=4,
                                grad_accum_steps=2, shuffle_seed=None,
                                shard_by_host=False, pack=pack)
        disk = StreamingTokenDataset(store, micro_batch_size=4,
                                     grad_accum_steps=2, shuffle_seed=None,
                                     shard_by_host=False)

    assert disk.steps_per_epoch() == mem.steps_per_epoch()
    for b_mem, b_disk in zip(mem.epoch(0), disk.epoch(0)):
        assert set(b_mem) == set(b_disk)
        for k in b_mem:
            np.testing.assert_array_equal(b_disk[k], b_mem[k], err_msg=k)


def test_streaming_keeps_empty_docs_in_unpacked_mode(tmp_path):
    """Row-count parity: TokenBatchDataset keeps empty docs as all-pad rows
    in unpacked mode, so the writer must too (packed mode drops them, same
    as pack_sequences)."""
    docs = _docs(16)
    docs[5] = []
    store = str(tmp_path / "store")
    meta = write_token_store(iter(docs), store, seq_len=32, pad_id=0)
    assert meta["n_rows"] == len(docs)
    mem = TokenBatchDataset(sequences=docs, seq_len=32, pad_id=0,
                            micro_batch_size=4, shuffle_seed=5,
                            shard_by_host=False)
    disk = StreamingTokenDataset(store, micro_batch_size=4, shuffle_seed=5,
                                 shard_by_host=False)
    assert disk.steps_per_epoch() == mem.steps_per_epoch()
    for b_mem, b_disk in zip(mem.epoch(0), disk.epoch(0)):
        np.testing.assert_array_equal(b_disk["input_ids"], b_mem["input_ids"])
        np.testing.assert_array_equal(b_disk["loss_mask"], b_mem["loss_mask"])


def test_empty_store_raises_clearly(tmp_path):
    store = str(tmp_path / "store")
    write_token_store(iter([]), store, seq_len=32, pad_id=0)
    with pytest.raises(ValueError, match="empty"):
        StreamingTokenDataset(store, micro_batch_size=4, shard_by_host=False)


def test_streaming_resume_skip_steps(tmp_path):
    store = str(tmp_path / "store")
    write_token_store(iter(_docs()), store, seq_len=32, pad_id=0)
    ds = StreamingTokenDataset(store, micro_batch_size=4, shuffle_seed=3,
                               shard_by_host=False)
    full = list(ds.epoch(1))
    resumed = list(ds.epoch(1, skip_steps=3))
    assert len(resumed) == len(full) - 3
    np.testing.assert_array_equal(resumed[0]["input_ids"],
                                  full[3]["input_ids"])


def test_streaming_writer_is_chunked_and_store_is_memmapped(tmp_path):
    """The writer consumes a pure iterator (nothing to re-read) chunk by
    chunk, and the dataset reads through np.memmap — host RAM holds the
    epoch permutation, not the tokens."""
    store = str(tmp_path / "store")
    n_docs, seq_len = 5000, 64

    def gen():
        g = np.random.default_rng(1)
        for _ in range(n_docs):
            yield list(map(int, g.integers(1, 250, size=30)))

    meta = write_token_store(gen(), store, seq_len=seq_len, pad_id=0,
                             chunk_docs=256)
    assert meta["n_rows"] == n_docs
    assert os.path.getsize(os.path.join(store, "ids.bin")) == (
        n_docs * seq_len * 4)
    ds = StreamingTokenDataset(store, micro_batch_size=8,
                               shard_by_host=False)
    assert isinstance(ds._ids, np.memmap)
    batch = next(ds.epoch(0))
    assert batch["input_ids"].shape == (1, 8, seq_len)


def test_train_step_runs_from_streaming_store(tmp_path):
    """End-to-end: a jitted train step consumes memmap-backed batches."""
    import jax

    from dlti_tpu.config import MODEL_PRESETS, LoRAConfig, OptimizerConfig
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.training import (
        build_optimizer, create_train_state, make_train_step,
    )

    store = str(tmp_path / "store")
    write_token_store(iter(_docs(48, hi=30)), store, seq_len=32, pad_id=0,
                      pack=True, chunk_docs=16)
    ds = StreamingTokenDataset(store, micro_batch_size=2,
                               grad_accum_steps=2, shard_by_host=False)

    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, LoRAConfig(r=4, alpha=8, dropout=0.0))
    tx = build_optimizer(OptimizerConfig())
    rng = jax.random.PRNGKey(0)
    state = create_train_state(rng, model, tx, (2, 32), lora_enabled=True)
    step = jax.jit(make_train_step(model, accum_steps=2))
    losses = []
    for i, batch in enumerate(ds.epoch(0)):
        if i == 3:
            break
        state, m = step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))
    assert len(losses) == 3 and all(np.isfinite(losses))
