"""int8 frozen-base LoRA training (the QLoRA idea, TPU-style).

Grads flow only to the LoRA factors, so the frozen base may rest in HBM as
weight-only int8 (``TrainConfig.quantize_frozen_base``) — the lever that
frees ~half the base-weight HBM for activation saving at 7B (the measured
MFU wall, results/mfu_investigation_r02.json). Contracts under test:

* quant leaves partition into the frozen subset; only LoRA trains
* the int8-frozen loss trajectory tracks the bf16 trajectory closely
* merged export dequantizes back to a standard compute-dtype tree
* the sharded (ZeRO-3 x TP) int8 step matches the single-device int8 step
* the Trainer wires it end to end (train -> resume -> export)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig,
    Config,
    DataConfig,
    LoRAConfig,
    MODEL_PRESETS,
    OptimizerConfig,
    ParallelConfig,
    TrainConfig,
    ZeROStage,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.models.lora import merge_lora_params
from dlti_tpu.models.quantization import (
    is_quant_node,
    quantize_params_int8,
)
from dlti_tpu.training import build_optimizer, create_train_state, make_train_step
from dlti_tpu.training.state import partition_params

# Big enough that projections pass the >=64KiB quantization threshold.
CFG = dataclasses.replace(
    MODEL_PRESETS["llama_tiny"], hidden_size=128, intermediate_size=256,
    vocab_size=1024)
LORA = LoRAConfig(r=4, alpha=8, dropout=0.0)


def _state(rng, quantize: bool):
    model = LlamaForCausalLM(CFG, LORA)
    tx = build_optimizer(OptimizerConfig(warmup_steps=2))
    state = create_train_state(rng, model, tx, (4, 32), lora_enabled=True)
    if quantize:
        state = state.replace(params=quantize_params_int8(state.params))
    return model, state


def _batch(seed, accum=1, bs=4, seq=32):
    r = jax.random.PRNGKey(seed)
    return {
        "input_ids": jax.random.randint(r, (accum, bs, seq), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((accum, bs, seq), jnp.int32),
    }


def _run(rng, quantize: bool, steps: int):
    model, state = _state(rng, quantize)
    step = jax.jit(make_train_step(model, accum_steps=1))
    losses = []
    batch = _batch(0)  # fixed batch: memorization must drive loss down
    for i in range(steps):
        state, m = step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(m["loss"]))
    return state, losses


def test_quant_leaves_partition_as_frozen(rng):
    _, state = _state(rng, quantize=True)
    trainable, frozen = partition_params(state.params, lora_enabled=True)
    assert trainable, "LoRA factors must stay trainable"
    for key in trainable:
        assert key[-1] in ("lora_a", "lora_b")
    # Every quantized kernel's q/scale pair landed in the frozen subset.
    q_keys = [k for k in frozen if k[-1] == "q"]
    assert q_keys, "expected int8 kernels in the frozen subset"
    for k in q_keys:
        assert frozen[k].dtype == jnp.int8
        assert k[:-1] + ("scale",) in frozen


@pytest.mark.slow
def test_int8_frozen_loss_tracks_bf16(rng):
    """Quantization noise on the frozen base must be benign: the int8 run's
    loss trajectory stays within a small band of the bf16 run's."""
    steps = 12
    _, ref = _run(rng, quantize=False, steps=steps)
    _, q = _run(rng, quantize=True, steps=steps)
    assert all(np.isfinite(q)), q
    # Same data, same init (B=0 start): per-step losses track closely.
    for i, (a, b) in enumerate(zip(ref, q)):
        assert abs(a - b) / a < 0.02, f"step {i}: bf16 {a} vs int8 {b}"
    # And training actually trains.
    assert q[-1] < q[0]


def test_merged_export_is_dequantized_and_close(rng):
    _, state = _state(rng, quantize=True)
    # Give LoRA a nonzero delta so the merge is exercised for real.
    trainable, frozen = partition_params(state.params, lora_enabled=True)
    trainable = {
        k: jax.random.normal(jax.random.fold_in(rng, i), v.shape, v.dtype) * 0.02
        for i, (k, v) in enumerate(sorted(trainable.items()))
    }
    from dlti_tpu.training.state import combine_params

    params = combine_params(trainable, frozen)
    merged = merge_lora_params(params, alpha=LORA.alpha)

    leaves = jax.tree_util.tree_leaves_with_path(merged)
    assert not any(is_quant_node(v) for _, v in leaves)
    for path, v in leaves:
        assert v.dtype != jnp.int8, path

    # Against the dequantized-then-merged reference.
    from dlti_tpu.models.quantization import dequantize_params

    ref = merge_lora_params(
        combine_params(trainable, dequantize_params(frozen)), alpha=LORA.alpha)
    k = "q_proj"
    a = np.asarray(
        merged["model"]["layers_0"]["attn"][k]["kernel"], np.float32)
    b = np.asarray(ref["model"]["layers_0"]["attn"][k]["kernel"], np.float32)
    np.testing.assert_allclose(a, b, atol=1e-2)


@pytest.mark.slow
def test_sharded_int8_matches_single_device(rng):
    from dlti_tpu.parallel import build_mesh, make_sharded_train_step, shard_train_state

    batch = _batch(7, accum=2, bs=8)
    # Single-device int8 ground truth.
    model, state = _state(rng, quantize=True)
    step = jax.jit(make_train_step(model, accum_steps=2))
    ref_metrics = None
    for i in range(2):
        state, ref_metrics = step(state, batch, jax.random.fold_in(rng, i))

    cfg = Config(
        model=CFG, lora=LORA, optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=4, tensor=2),
        train=TrainConfig(micro_batch_size=8, grad_accum_steps=2,
                          quantize_frozen_base="int8"),
    )
    model, sh_state = _state(rng, quantize=True)
    mesh = build_mesh(cfg.parallel)
    sh_state = shard_train_state(sh_state, cfg, mesh)
    sh_step = make_sharded_train_step(model, sh_state, cfg, mesh,
                                      accum_steps=2, donate=False)
    metrics = None
    for i in range(2):
        sh_state, metrics = sh_step(sh_state, batch, jax.random.fold_in(rng, i))
    np.testing.assert_allclose(float(metrics["loss"]),
                               float(ref_metrics["loss"]), rtol=2e-4)


def test_trainer_requires_lora_for_quantized_base(tmp_path):
    cfg = Config(
        model=CFG, lora=LoRAConfig(enabled=False),
        train=TrainConfig(quantize_frozen_base="int8"),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt")),
    )
    from dlti_tpu.training.trainer import Trainer

    with pytest.raises(ValueError, match="requires LoRA"):
        Trainer(cfg).init_state()


@pytest.mark.slow
def test_trainer_int8_train_resume_export(tmp_path):
    """End to end through the Trainer: quantized base training runs,
    checkpoints, resumes, and exports a standard merged tree."""
    from dlti_tpu.checkpoint import export_merged_model, load_exported_model
    from dlti_tpu.data import ByteTokenizer, make_batches
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=dataclasses.replace(CFG, vocab_size=258),
        lora=LORA,
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(zero_stage=ZeROStage.ZERO2, data=8),
        data=DataConfig(max_seq_len=32, tokenizer="byte"),
        checkpoint=CheckpointConfig(
            output_dir=str(tmp_path / "ckpt"), save_steps=2,
            save_total_limit=2, async_save=False),
        train=TrainConfig(num_epochs=1, micro_batch_size=8,
                          grad_accum_steps=1, max_steps=4,
                          logging_steps=100, quantize_frozen_base="int8",
                          metrics_csv=str(tmp_path / "metrics.csv")),
    )
    texts = [f"question {i}: the answer is {2 * i}." for i in range(200)]
    ds = make_batches(texts, ByteTokenizer(), seq_len=32,
                      micro_batch_size=8, shard_by_host=False)
    state, record = Trainer(cfg).train(dataset=ds)
    assert np.isfinite(record.final_loss)

    # Resume picks up the quantized tree from the checkpoint.
    cfg2 = cfg.replace(train=dataclasses.replace(cfg.train, max_steps=6))
    state2, _ = Trainer(cfg2).train(dataset=ds)
    assert int(state2.step) == 6

    out = export_merged_model(str(tmp_path / "export"), state2.params, cfg2)
    params, _ = load_exported_model(out)
    for path, v in jax.tree_util.tree_leaves_with_path(params):
        assert v.dtype != jnp.int8, path
