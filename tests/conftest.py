"""Test harness: simulate an 8-device TPU mesh on CPU.

The reference has zero tests (SURVEY.md §4); this suite is the from-scratch
strategy it prescribes: unit tests per component, sharding-equivalence tests
(N-device step == single-device step) on a virtual device mesh, golden-loss
regression, and end-to-end train→checkpoint→resume→serve smokes.

Env vars must be set before jax initializes, hence module scope here.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags += " --xla_force_host_platform_device_count=8"
if "xla_backend_optimization_level" not in flags:
    # The suite is XLA-compile-bound on small runners and tests OUR code,
    # not XLA's optimizer: backend opt level 0 cuts cold-compile wall time
    # ~30% with identical test outcomes (numerics still honor
    # jax_default_matmul_precision below). Remove via
    # XLA_FLAGS=--xla_backend_optimization_level=1 if ever suspect.
    flags += " --xla_backend_optimization_level=0"
os.environ["XLA_FLAGS"] = flags

import jax  # noqa: E402
import pytest  # noqa: E402

# The env var alone is not enough in this image (a site hook re-forces the
# TPU plugin platform on jax import); the config update wins as long as the
# backend has not been initialized yet.
jax.config.update("jax_platforms", "cpu")

# The CPU backend downcasts fp32 matmul inputs under the default precision
# (≈bf16, ~7e-3 error); correctness tests need true fp32 matmuls.
jax.config.update("jax_default_matmul_precision", "highest")

# The suite is XLA-compile-bound on a 1-CPU runner; the persistent cache
# replays every test's compiles after the first run. Threshold lowered
# from the entry points' 5 s: test-sized programs compile in 0.5–5 s each
# but there are hundreds of them.
from dlti_tpu.utils.platform import enable_compilation_cache  # noqa: E402

enable_compilation_cache(subdir="xla-tests", min_compile_secs=0.5)


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(0)


def make_packed_segments(b, s, n_docs=3, seed=0):
    """Shared packed-batch layout for attention tests: contiguous docs
    1..n_docs with random cut points, trailing padding id 0."""
    import numpy as np
    import jax.numpy as jnp

    gen = np.random.default_rng(seed)
    segs = np.zeros((b, s), dtype=np.int32)
    for row in range(b):
        cuts = np.sort(gen.choice(np.arange(4, s - 4), n_docs, replace=False))
        prev, sid = 0, 1
        for c in cuts:
            segs[row, prev:c] = sid
            prev, sid = c, sid + 1
    return jnp.asarray(segs)
