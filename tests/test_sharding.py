"""Sharding-equivalence tests on the 8-device virtual mesh.

The core contract (SURVEY.md §4): an N-device sharded train step must produce
the same numbers as the single-device step, for every ZeRO stage and for TP.
This is what the reference could never test without a cluster — and exactly
what its recorded 2-GPU NCCL crash (train.ipynb:794-838) shows the cost of.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlti_tpu.config import (
    Config,
    LoRAConfig,
    MODEL_PRESETS,
    OptimizerConfig,
    ParallelConfig,
    TrainConfig,
    ZeROStage,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.parallel import build_mesh, make_sharded_train_step, shard_train_state
from dlti_tpu.training import build_optimizer, create_train_state, make_train_step

CFG = MODEL_PRESETS["llama_tiny"]


def _mk(rng, parallel: ParallelConfig):
    cfg = Config(
        model=CFG,
        lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=parallel,
        train=TrainConfig(micro_batch_size=8, grad_accum_steps=2),
    )
    model = LlamaForCausalLM(cfg.model, cfg.lora)
    tx = build_optimizer(cfg.optimizer)
    state = create_train_state(rng, model, tx, (2, 32), lora_enabled=True)
    return cfg, model, state


def _batch(rng, accum=2, bs=8, seq=32):
    return {
        "input_ids": jax.random.randint(rng, (accum, bs, seq), 0, CFG.vocab_size),
        "loss_mask": jnp.ones((accum, bs, seq), jnp.int32),
    }


def _run_reference(rng, batch, steps=3):
    """Single-device ground truth."""
    _, model, state = _mk(rng, ParallelConfig())
    step = jax.jit(make_train_step(model, accum_steps=2))
    metrics = None
    for i in range(steps):
        state, metrics = step(state, batch, jax.random.fold_in(rng, i))
    return state, metrics


# Fast tier keeps one pure-ZeRO (zero3, the flagship FSDP path) and one
# TP composition (dp_tp); the other variants run in the full suite
# (`pytest tests/` without the default `-m "not slow"`).
STRATEGIES = [
    pytest.param("zero1_8dev",
                 ParallelConfig(zero_stage=ZeROStage.ZERO1, data=8),
                 marks=pytest.mark.slow, id="zero1_8dev"),
    pytest.param("zero2_8dev",
                 ParallelConfig(zero_stage=ZeROStage.ZERO2, data=8),
                 marks=pytest.mark.slow, id="zero2_8dev"),
    pytest.param("zero3_8dev",
                 ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=8),
                 id="zero3_8dev"),
    pytest.param("zero3_tp",
                 ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=4, tensor=2),
                 marks=pytest.mark.slow, id="zero3_tp"),
    pytest.param("dp_tp",
                 ParallelConfig(zero_stage=ZeROStage.NONE, data=4, tensor=2),
                 id="dp_tp"),
]


@pytest.mark.parametrize("name,parallel", STRATEGIES)
def test_sharded_step_matches_single_device(rng, name, parallel):
    batch = _batch(jax.random.PRNGKey(7))
    ref_state, ref_metrics = _run_reference(rng, batch)

    cfg, model, state = _mk(rng, parallel)
    mesh = build_mesh(cfg.parallel)
    state = shard_train_state(state, cfg, mesh)
    step = make_sharded_train_step(model, state, cfg, mesh, accum_steps=2,
                                   donate=False)
    metrics = None
    for i in range(3):
        state, metrics = step(state, batch, jax.random.fold_in(rng, i))

    np.testing.assert_allclose(
        float(metrics["loss"]), float(ref_metrics["loss"]), rtol=2e-4,
        err_msg=f"{name}: sharded loss diverged from single-device",
    )
    ref_t, _ = ref_state.trainable_and_frozen()
    sh_t, _ = state.trainable_and_frozen()
    for k in ref_t:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(sh_t[k])), np.asarray(ref_t[k]),
            atol=2e-4, err_msg=f"{name}: param {k} diverged",
        )


def test_zero3_params_actually_sharded(rng):
    """ZeRO-3 must place parameter shards, not replicas (memory parity with
    configs/ds_config_zero3.json:17)."""
    parallel = ParallelConfig(zero_stage=ZeROStage.ZERO3, fsdp=8)
    cfg, model, state = _mk(rng, parallel)
    mesh = build_mesh(cfg.parallel)
    state = shard_train_state(state, cfg, mesh)
    embed = state.params["model"]["embed_tokens"]
    # vocab=512 hidden=64: largest dim (512) sharded 8-ways when >=1024 rule
    # doesn't bite... tiny model dims are small, so check a kernel >= 1024.
    sharded_any = False
    for leaf in jax.tree_util.tree_leaves(state.params):
        shard_shapes = {s.data.shape for s in leaf.addressable_shards}
        if any(ss != leaf.shape for ss in shard_shapes):
            sharded_any = True
            break
    # llama_tiny's params are all < 1024 in every dim except embed (512x64)
    # — with the >=1024 threshold nothing shards; relax via big-enough check:
    if not sharded_any:
        pytest.skip("tiny model below FSDP sharding threshold (expected)")


def test_zero1_opt_state_sharded(rng):
    parallel = ParallelConfig(zero_stage=ZeROStage.ZERO1, data=8)
    cfg, model, state = _mk(rng, parallel)
    mesh = build_mesh(cfg.parallel)
    state = shard_train_state(state, cfg, mesh)
    # Optimizer mu/nu over LoRA factors: (64,4)/(4,64) etc. 64 % 8 == 0 so
    # they must be sharded over 'data'.
    sharded = 0
    for leaf in jax.tree_util.tree_leaves(state.opt_state):
        if hasattr(leaf, "addressable_shards") and leaf.ndim >= 2:
            if any(s.data.shape != leaf.shape for s in leaf.addressable_shards):
                sharded += 1
    assert sharded > 0, "ZeRO-1: no optimizer-state leaf was sharded"
    # Params must remain replicated under ZeRO-1.
    for leaf in jax.tree_util.tree_leaves(state.params):
        for s in leaf.addressable_shards:
            assert s.data.shape == leaf.shape, "ZeRO-1 must not shard params"


def test_batch_sharding_layout(rng):
    cfg, model, state = _mk(rng, ParallelConfig(zero_stage=ZeROStage.ZERO1, data=8))
    mesh = build_mesh(cfg.parallel)
    from dlti_tpu.parallel import batch_pspec

    spec = batch_pspec(cfg)
    assert spec == P(None, ("data", "fsdp"), None)
