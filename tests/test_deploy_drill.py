"""Headline continuous-delivery drill (slow tier): a live train->serve
loop where chaos poisons one checkpoint and the canary gates keep it off
the fleet.

Everything real except the wall clock: a drill trainer writes committed
train-state checkpoints into the watch dir (``save_train_state``), the
controller exports candidates host-side (``export_params_host``),
canaries them on real ``InferenceEngine`` instances against real shadow
traffic mirrored off a real two-replica ``ReplicatedEngine``, and
promotes through the real per-swap-verified ``request_reload`` path.

Two poison variants, per the sentinel chaos taxonomy:

* **nan-grad**: one checkpoint is saved with a NaN param leaf (a
  nonfinite update that slipped past training), then training rolls the
  params back in memory and continues clean. The canary's *numeric*
  gate rejects it at the probe stage.
* **param-flip**: the pipeline is frozen (lr=0) so every clean
  checkpoint is bit-identical, and one checkpoint gets a single
  *exponent* bit flipped in one param element. The *drift* gate (pinned
  greedy probes vs the incumbent) rejects it. The exponent bit — not
  the injector's lowest-mantissa SDC bit — is deliberate: a canary
  judges behavior, so the drill flips a bit that moves logits; the
  bit-exact silent flips are the cross-rank digest probe's job
  (``training.sentinel``), not the canary's.

Both variants assert the full contract: at least one booked rollback,
the rejected export quarantined, the fleet's incumbent digest unchanged
until a later clean checkpoint promotes, and ZERO client-visible errors
throughout.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training.train_state import TrainState

from dlti_tpu.checkpoint.store import (
    load_pytree, manifest_digest, save_pytree, save_train_state,
)
from dlti_tpu.config import MODEL_PRESETS, DeployConfig
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import (
    EngineConfig, InferenceEngine, ReplicatedEngine, SamplingParams,
)
from dlti_tpu.serving import deploy as deploy_mod
from dlti_tpu.serving.deploy import DeploymentController

pytestmark = pytest.mark.slow

CFG = MODEL_PRESETS["llama_tiny"]


@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _ec():
    return EngineConfig(max_seqs=4, block_size=8, num_blocks=64,
                        max_model_len=128, cache_dtype="float32",
                        eos_token_id=-1)


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _first_leaf_path(params):
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return flat[0][0]


def _with_leaf(params, poison):
    """params with its first leaf replaced by poison(leaf)."""
    target = _first_leaf_path(params)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: poison(leaf) if path == target else leaf,
        params)


def _nan_leaf(leaf):
    return jnp.full_like(leaf, jnp.nan)


def _exponent_flip_leaf(leaf):
    host = np.array(jax.device_get(leaf), dtype=np.float32).copy()
    flat = host.reshape(-1)
    bits = flat.view(np.uint32)
    bits[0] ^= np.uint32(1) << np.uint32(28)
    return jnp.asarray(host)


class DrillTrainer:
    """Frozen-pipeline drill trainer: every clean save is bit-identical;
    a poisoned save corrupts the params, writes the committed
    checkpoint, then rolls the corruption back in memory (the in-memory
    state stays healthy — the *artifact* is what's bad)."""

    def __init__(self, watch_dir, params):
        self.watch_dir = watch_dir
        self.state = TrainState.create(
            apply_fn=lambda *a, **k: None, params=params,
            tx=optax.sgd(0.0))

    def save(self, step, poison=None):
        params = self.state.params
        if poison is not None:
            params = _with_leaf(params, poison)
        save_train_state(self.watch_dir, step,
                         self.state.replace(params=params),
                         keep=None, async_save=False)


def _run_drill(tmp_path, tiny_params, *, poison, drift_limit,
               reject_reason_prefix):
    watch = str(tmp_path / "watch")
    os.makedirs(watch)
    incumbent = save_pytree(str(tmp_path / "incumbent"),
                            jax.device_get(tiny_params))

    rep = ReplicatedEngine(CFG, tiny_params, _ec(), replicas=2, tensor=1,
                           devices=jax.devices()[:2])

    def canary_factory(export_dir):
        cparams = load_pytree(export_dir, verify=True)
        return InferenceEngine(CFG, cparams, _ec())

    clk = _Clock()
    ctrl = DeploymentController(
        rep,
        DeployConfig(enabled=True, watch_dir=watch,
                     export_dir=str(tmp_path / "exports"),
                     poll_interval_s=1.0, canary_shadow_frac=1.0,
                     canary_min_requests=2, canary_max_wait_s=300.0,
                     promote_max_logprob_drift=drift_limit,
                     probe_prompts=2, probe_prompt_tokens=4,
                     probe_max_tokens=3, promote_backoff_s=0.0),
        canary_factory=canary_factory, incumbent_dir=incumbent,
        clock=clk)

    trainer = DrillTrainer(watch, tiny_params)
    live_reqs = []
    sp = SamplingParams(temperature=0.0, max_tokens=4)

    def pump_round():
        """One beat of the live loop: client traffic lands (and gets
        mirrored by the tap mid-canary), the fleet serves it to
        completion, then the controller ticks."""
        reqs = [rep.submit([1, 2, 3, 4, 5], sp) for _ in range(2)]
        for _ in range(2000):
            if all(r.done for r in reqs) and not rep.has_work:
                break
            rep.step()
        assert all(r.done for r in reqs)
        live_reqs.extend(reqs)
        clk.t += 2.0
        ctrl.tick()

    def drive_until(pred, what, max_rounds=60):
        for _ in range(max_rounds):
            if pred():
                return
            pump_round()
        raise AssertionError(
            f"drill never reached {what}: state={ctrl.state} "
            f"status={ctrl.status()}")

    rollbacks0 = deploy_mod.rollbacks_total.value

    # ---- clean checkpoint 1: watched, canaried, promoted ------------
    trainer.save(1)
    drive_until(lambda: ctrl.incumbent_step == 1, "promotion of step 1")
    digest1 = ctrl.incumbent_digest
    assert digest1 == manifest_digest(
        os.path.join(str(tmp_path / "exports"), "step-1"))

    # ---- poisoned checkpoint 2: caught, rolled back, quarantined ----
    trainer.save(2, poison=poison)
    drive_until(lambda: 2 in ctrl._refused, "rejection of step 2")
    assert deploy_mod.rollbacks_total.value - rollbacks0 >= 1
    res = ctrl.status()["last_result"]
    assert res["verdict"] == "rolled-back" and res["step"] == 2
    assert any(r.startswith(reject_reason_prefix) for r in res["reasons"]), res
    # The incumbent never moved; the rejected export went to forensics.
    assert ctrl.incumbent_step == 1
    assert ctrl.incumbent_digest == digest1
    assert not os.path.exists(
        os.path.join(str(tmp_path / "exports"), "step-2"))
    qdir = os.path.join(str(tmp_path / "exports"), "_quarantine")
    assert any(e.startswith("step-2") for e in os.listdir(qdir))

    # The poisoned step stays refused even though it is still the
    # newest committed checkpoint in the watch dir.
    for _ in range(3):
        pump_round()
    assert ctrl.state == "idle" and ctrl.incumbent_step == 1

    # ---- clean checkpoint 3: the pipeline recovers ------------------
    trainer.save(3)
    drive_until(lambda: ctrl.incumbent_step == 3, "promotion of step 3")
    assert ctrl.incumbent_digest == manifest_digest(
        os.path.join(str(tmp_path / "exports"), "step-3"))
    assert deploy_mod.incumbent_step_gauge.value == 3

    # ---- the client saw NOTHING -------------------------------------
    assert live_reqs, "drill produced no client traffic"
    for req in live_reqs:
        assert req.finish_reason not in (None, "error"), req.request_id
        assert req.output_token_ids
        assert all(np.isfinite(lp) for lp in req.output_logprobs), \
            f"nonfinite logprob reached client request {req.request_id}"
        assert not req.shadow

    ctrl.stop()
    return ctrl


def test_drill_nan_grad_checkpoint_is_caught(tmp_path, tiny_params):
    ctrl = _run_drill(tmp_path, tiny_params, poison=_nan_leaf,
                      drift_limit=0.25, reject_reason_prefix="numeric:")
    # The numeric gate fired at the probe stage: nonfinite outputs.
    assert ctrl.status()["counters"]["promotions"] >= 2


def test_drill_param_flip_checkpoint_is_caught(tmp_path, tiny_params):
    # Frozen pipeline: clean checkpoints are bit-identical, so the
    # tightest possible drift gate is sound — and the flipped exponent
    # bit must register as nonzero greedy drift against the incumbent.
    _run_drill(tmp_path, tiny_params, poison=_exponent_flip_leaf,
               drift_limit=1e-6, reject_reason_prefix="drift:")
