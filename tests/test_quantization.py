"""Weight-only int8 serving: quantization round-trip + engine integration."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.models.quantization import (
    dequantize_params,
    quantization_error,
    quantize_params_int8,
)
from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams

# Quantization only touches leaves >= 64KiB; bump the tiny preset's sizes
# enough that the projections qualify.
CFG = dataclasses.replace(
    MODEL_PRESETS["llama_tiny"], hidden_size=128, intermediate_size=256,
    vocab_size=1024)


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def test_quantize_leaf_selection_and_error(model_and_params):
    _, params = model_and_params
    qp = quantize_params_int8(params)
    # Kernels became {"q","scale"} int8 nodes; norm scales stayed float.
    qk = qp["model"]["layers_0"]["attn"]["q_proj"]["kernel"]
    assert set(qk.keys()) == {"q", "scale"} and qk["q"].dtype == jnp.int8
    assert qp["model"]["layers_0"]["input_norm"]["scale"].dtype != jnp.int8
    # int8 symmetric absmax keeps per-leaf relative RMS error small.
    assert quantization_error(params, qp) < 0.01


def test_dequantize_roundtrip_close(model_and_params):
    _, params = model_and_params
    deq = dequantize_params(quantize_params_int8(params), jnp.float32)
    a = np.asarray(params["model"]["layers_0"]["mlp"]["gate_proj"]["kernel"])
    b = np.asarray(deq["model"]["layers_0"]["mlp"]["gate_proj"]["kernel"])
    scale = np.abs(a).max(axis=0)
    np.testing.assert_allclose(a, b, atol=float(scale.max()) / 127 + 1e-7)


@pytest.mark.slow
def test_int8_engine_logits_close_and_serves(model_and_params):
    model, params = model_and_params
    ec = dict(max_seqs=2, block_size=8, num_blocks=32, max_model_len=48,
              cache_dtype="float32", eos_token_id=-1)
    prompts = [[3, 1, 4, 1, 5, 9], [2, 7, 1, 8]]
    sp = SamplingParams(temperature=0.0, max_tokens=6)

    fp = InferenceEngine(CFG, params, EngineConfig(**ec))
    q8 = InferenceEngine(CFG, params, EngineConfig(quantization="int8", **ec))
    # Weights really rest as int8.
    assert (q8.params["model"]["layers_0"]["attn"]["q_proj"]["kernel"]["q"]
            .dtype == jnp.int8)

    want = fp.generate(prompts, sp)
    got = q8.generate(prompts, sp)
    # Random tiny weights leave tokens near-tied, so compare logprob
    # trajectories rather than exact argmax tokens.
    for g, w in zip(got, want):
        assert len(g.output_token_ids) == len(w.output_token_ids)
        np.testing.assert_allclose(g.output_logprobs, w.output_logprobs,
                                   atol=0.35)


@pytest.mark.slow
def test_int8_tp_engine_matches_unsharded_int8(model_and_params):
    """int8 weights compose with TP: quantized {"q","scale"} leaves shard
    like their fp ancestors (scales follow output channels, replicate for
    row-parallel kernels) and TP=2 generation matches the unsharded int8
    engine token-for-token."""
    from dlti_tpu.config import ParallelConfig
    from dlti_tpu.parallel import build_mesh

    _, params = model_and_params
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=48, cache_dtype="float32",
                      eos_token_id=-1, quantization="int8")
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8]]
    sp = SamplingParams(temperature=0.0, max_tokens=5)

    want = InferenceEngine(CFG, params, ec).generate(prompts, sp)

    mesh = build_mesh(ParallelConfig(tensor=2), devices=jax.devices()[:2])
    tp_engine = InferenceEngine(CFG, params, ec, mesh=mesh)
    # Quantized kernels really are sharded: q_proj q-leaf over its out dim,
    # its scale alongside; down_proj (row-parallel) scale replicated.
    qp = tp_engine.params["model"]["layers_0"]["attn"]["q_proj"]["kernel"]
    assert qp["q"].sharding.spec[1] == "tensor"
    assert qp["scale"].sharding.spec[1] == "tensor"
    dp = tp_engine.params["model"]["layers_0"]["mlp"]["down_proj"]["kernel"]
    assert dp["q"].sharding.spec[0] == "tensor"
    assert all(s is None for s in dp["scale"].sharding.spec)
    got = tp_engine.generate(prompts, sp)
    for g, w in zip(got, want):
        assert g.output_token_ids == w.output_token_ids


@pytest.mark.slow
def test_int8_moe_engine_serves(model_and_params):
    """MoE int8 serving: experts quantize (per-expert scales), the router
    stays fp32, and generation runs."""
    moe_cfg = dataclasses.replace(
        MODEL_PRESETS["mixtral_tiny"], hidden_size=128, intermediate_size=256,
        vocab_size=1024)
    model = LlamaForCausalLM(moe_cfg, None)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    qp = quantize_params_int8(params)
    mlp = qp["model"]["layers_0"]["mlp"]
    assert mlp["w1"]["q"].dtype == jnp.int8
    assert mlp["w1"]["scale"].shape == (4, 1, 256)  # per-expert-channel
    assert mlp["router"].dtype != jnp.int8  # excluded

    engine = InferenceEngine(moe_cfg, params, EngineConfig(
        max_seqs=2, block_size=8, num_blocks=32, max_model_len=48,
        cache_dtype="float32", eos_token_id=-1, quantization="int8"))
    [r] = engine.generate([[3, 1, 4, 1, 5]],
                          SamplingParams(temperature=0.0, max_tokens=5))
    assert len(r.output_token_ids) == 5
