"""HBM memory ledger: per-owner attribution, conservation, OOM
forensics, and headroom-aware admission (tiny model, CPU).

The acceptance bar for the ledger is *conservation*: every snapshot's
bucket map (owners + untracked + residual) sums to bytes-in-use exactly
— on a synthetic tree, on a live Trainer, and on a live server where
``/debug/memory`` and ``/metrics`` must tell the same story. The
consumers ride along: an injected ``hbm-squeeze`` OOM in training and a
RESOURCE_EXHAUSTED in the engine both leave a flight dump whose
``memory.json`` says where the memory went (and postmortem renders it),
and the engine defers admission under headroom pressure instead of
faulting — zero client-visible errors, proved with a chaos balloon.
"""

import http.client
import json
import os
import subprocess
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, FlightRecorderConfig, LoRAConfig,
    MODEL_PRESETS, TelemetryConfig, TrainConfig, WatchdogConfig,
)
from dlti_tpu.data.tokenizer import ByteTokenizer
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams
from dlti_tpu.serving.server import ServerConfig, make_server
from dlti_tpu.telemetry import memledger as ml
from dlti_tpu.telemetry.flightrecorder import (
    FlightRecorder, install as install_recorder, list_dumps, load_dump,
)
from dlti_tpu.telemetry.memledger import (
    MemoryBalloon, MemoryLedger, is_oom_error, tree_nbytes,
)
from dlti_tpu.telemetry.tracer import SpanTracer, configure_tracer, get_tracer
from dlti_tpu.training.chaos import SimulatedOOM, TrainFault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import memory_plan  # noqa: E402

CFG = MODEL_PRESETS["llama_tiny"]


def _assert_conserved(snap):
    """The ledger's core contract: buckets sum to bytes_in_use EXACTLY."""
    assert sum(snap["buckets"].values()) == snap["bytes_in_use"], \
        snap["buckets"]


# ----------------------------------------------------------------------
# Unit: attribution arithmetic on a synthetic tree
# ----------------------------------------------------------------------

def test_conservation_with_owners_untracked_and_carve():
    ledger = MemoryLedger()
    a = jax.block_until_ready(jnp.zeros((256, 64), jnp.float32))
    b = jax.block_until_ready(jnp.ones((128,), jnp.float32))
    stray = jax.block_until_ready(jnp.zeros((99,), jnp.float32))

    ledger.register("params", {"w": a, "b": b})
    snap = ledger.snapshot(top_k=4)
    assert snap["source"] in ("device", "live_arrays")
    assert snap["owners"]["params"]["bytes"] == int(a.nbytes) + int(b.nbytes)
    # The stray array is live but unowned -> untracked, never lost.
    assert snap["untracked_bytes"] >= int(stray.nbytes)
    _assert_conserved(snap)
    assert snap["num_live_arrays"] >= 3
    # top_k surfaces the largest unowned arrays with shape/dtype.
    assert all({"shape", "dtype", "nbytes", "per_device"} <= set(e)
               for e in snap["top_untracked_arrays"])

    # A carve moves bytes out of its parent without touching the total.
    ledger.register_carve("prefix_cache_hbm", "params", lambda: int(b.nbytes))
    snap2 = ledger.snapshot()
    assert snap2["owners"]["prefix_cache_hbm"]["bytes"] == int(b.nbytes)
    assert snap2["owners"]["prefix_cache_hbm"]["carved_from"] == "params"
    assert snap2["owners"]["params"]["bytes"] == int(a.nbytes)
    _assert_conserved(snap2)

    # An array registered under two owners is counted once (aliasing).
    ledger.register("optimizer_state", [a])
    snap3 = ledger.snapshot()
    assert snap3["owners"]["optimizer_state"]["bytes"] == 0
    _assert_conserved(snap3)


def test_disabled_ledger_is_inert():
    ledger = MemoryLedger(enabled=False)
    ledger.register("params", jnp.zeros((8,)))
    assert ledger.snapshot() == {}
    assert ledger.scalars() == {}
    assert ledger.to_dict() == {}
    assert ledger.headroom_bytes() is None


def test_headroom_and_peak_tracking():
    ledger = MemoryLedger()
    arr = jax.block_until_ready(jnp.zeros((1024,), jnp.float32))
    ledger.register("params", [arr])
    snap = ledger.snapshot()
    # CPU without a budget: capacity unknown -> headroom None (gating
    # consumers must skip, not treat as 0).
    if snap["source"] == "live_arrays":
        assert snap["headroom_bytes"] is None
    cap = snap["bytes_in_use"] + (8 << 20)
    ledger.set_capacity(cap)
    snap2 = ledger.snapshot()
    assert 0 < snap2["headroom_bytes"] <= cap
    assert snap2["peak_bytes"] >= snap["bytes_in_use"]
    s = ledger.scalars()
    assert s["hbm_headroom_bytes"] > 0
    assert 0.0 <= s["hbm_headroom_frac"] <= 1.0


def test_balloon_inflate_registers_and_deflate_releases():
    ledger = MemoryLedger()
    balloon = MemoryBalloon(ledger=ledger)
    balloon.inflate(1 << 20)
    assert balloon.nbytes >= 1 << 20
    snap = ledger.snapshot()
    assert snap["owners"]["chaos_balloon"]["bytes"] >= 1 << 20
    _assert_conserved(snap)
    balloon.deflate()
    assert balloon.nbytes == 0
    # Owner entry released with the bytes.
    assert "chaos_balloon" not in ledger.snapshot()["owners"]


def test_is_oom_error_classification():
    assert is_oom_error(MemoryError())
    assert is_oom_error(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert is_oom_error(SimulatedOOM("RESOURCE_EXHAUSTED: injected"))
    assert not is_oom_error(ValueError("bad shape"))
    assert not is_oom_error(RuntimeError("device disconnected"))


# ----------------------------------------------------------------------
# Watchdog: hbm_pressure rule
# ----------------------------------------------------------------------

def test_watchdog_hbm_pressure_rule():
    from dlti_tpu.telemetry import AnomalyWatchdog, TimeSeriesSampler

    cell = {"hbm_headroom_frac": 0.5}
    sampler = TimeSeriesSampler(interval_s=60.0)
    sampler.add_source(lambda: dict(cell))
    wd = AnomalyWatchdog(
        WatchdogConfig(enabled=True, hbm_headroom_floor_frac=0.1), sampler)
    sampler.sample_now()
    assert [a for a in wd.check_now() if a["rule"] == "hbm_pressure"] == []
    cell["hbm_headroom_frac"] = 0.04   # below the 10% floor
    sampler.sample_now()
    fired = [a for a in wd.check_now() if a["rule"] == "hbm_pressure"]
    assert len(fired) == 1
    assert "headroom" in fired[0]["message"]
    # Edge-triggered; recovery re-arms.
    sampler.sample_now()
    assert [a for a in wd.check_now() if a["rule"] == "hbm_pressure"] == []
    cell["hbm_headroom_frac"] = 0.6
    sampler.sample_now()
    wd.check_now()
    cell["hbm_headroom_frac"] = 0.02
    sampler.sample_now()
    assert [a for a in wd.check_now() if a["rule"] == "hbm_pressure"]


# ----------------------------------------------------------------------
# Live engine + server: attribution, /debug/memory vs /metrics
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    rng = jax.random.PRNGKey(0)
    return model.init(rng, jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture(scope="module")
def engine(tiny_params):
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=64,
                      max_model_len=64, cache_dtype="float32",
                      eos_token_id=-1, admit_min_headroom_frac=0.25)
    return InferenceEngine(CFG, tiny_params, ec)


@pytest.fixture(scope="module")
def live_server(tiny_params):
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    eng = InferenceEngine(CFG, tiny_params, ec)
    httpd, async_engine = make_server(
        eng, ByteTokenizer(),
        ServerConfig(host="127.0.0.1", port=0,
                     default_params=SamplingParams(max_tokens=8)))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield "127.0.0.1", port, eng
    httpd.shutdown()
    async_engine.shutdown()
    httpd.server_close()


def _get(host, port, path):
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("GET", path)
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_engine_ledger_owners_and_conservation(engine):
    assert engine.memledger.enabled
    snap = engine.memledger.snapshot()
    assert snap["owners"]["params"]["bytes"] > 0
    assert snap["owners"]["kv_block_pool"]["bytes"] > 0
    _assert_conserved(snap)
    # A decode pass doesn't break conservation (state churn, temp
    # arrays, donation all land in a bucket).
    r = engine.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                max_tokens=4))
    while engine.has_work:
        engine.step()
    assert r.done and len(r.output_token_ids) == 4
    _assert_conserved(engine.memledger.snapshot())


def test_server_debug_memory_and_metrics_agree(live_server):
    host, port, eng = live_server
    # Drive one real completion so the pools are exercised.
    conn = http.client.HTTPConnection(host, port, timeout=120)
    conn.request("POST", "/v1/completions",
                 json.dumps({"prompt": "hi", "max_tokens": 4,
                             "temperature": 0.0}),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200, resp.read()
    resp.read()
    conn.close()

    st, raw = _get(host, port, "/debug/memory")
    assert st == 200
    snap = json.loads(raw)
    _assert_conserved(snap)
    assert snap["owners"]["params"]["bytes"] > 0
    assert snap["owners"]["kv_block_pool"]["bytes"] > 0
    assert "ts" in snap

    # /metrics must tell the same story: refresh the gauges through the
    # same scalars() path the server's sampler runs, then compare the
    # stable owner (params never churns between the two scrapes).
    eng.memledger.scalars()
    st, raw = _get(host, port, "/metrics")
    assert st == 200
    text = raw.decode()
    samples = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            continue
        name, value = line.split()
        samples[name] = float(value)
    assert samples['dlti_hbm_bytes{owner="params"}'] == \
        snap["owners"]["params"]["bytes"]
    assert samples['dlti_hbm_bytes{owner="kv_block_pool"}'] == \
        snap["owners"]["kv_block_pool"]["bytes"]
    assert "dlti_hbm_peak_bytes" in samples
    assert "dlti_hbm_untracked_bytes" in samples
    assert samples["dlti_hbm_peak_bytes"] >= snap["owners"]["params"]["bytes"]


def test_server_debug_memory_404_when_disabled(tiny_params):
    ec = EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                      max_model_len=32, cache_dtype="float32",
                      eos_token_id=-1, memory_ledger=False)
    eng = InferenceEngine(CFG, tiny_params, ec)
    assert not eng.memledger.enabled
    httpd, async_engine = make_server(
        eng, ByteTokenizer(), ServerConfig(host="127.0.0.1", port=0))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        st, _ = _get("127.0.0.1", port, "/debug/memory")
        assert st == 404
    finally:
        httpd.shutdown()
        async_engine.shutdown()
        httpd.server_close()


# ----------------------------------------------------------------------
# Headroom-aware admission: defer, don't fault (chaos hbm-squeeze)
# ----------------------------------------------------------------------

def test_squeeze_defers_admission_with_zero_client_errors(engine):
    ledger = engine.memledger
    balloon = MemoryBalloon(ledger=ledger)
    balloon_bytes = 8 << 20
    try:
        # Gate off while capacity is unknown: requests flow normally.
        r1 = engine.submit([5, 6, 7], SamplingParams(temperature=0.0,
                                                     max_tokens=3))
        while engine.has_work:
            engine.step()
        assert r1.done and r1.finish_reason == "length"
        assert engine.stats.get("hbm_deferred_admissions", 0) == 0

        # Squeeze: balloon + a capacity placed so that headroom is below
        # 25% of capacity while inflated and above it once deflated, for
        # ANY base usage (cap in [(4/3)base, (4/3)(base+B))).
        base = ledger.snapshot()["bytes_in_use"]
        balloon.inflate(balloon_bytes)
        ledger.set_capacity((4 * base + 2 * balloon_bytes) // 3)

        r2 = engine.submit([1, 2, 3, 4], SamplingParams(temperature=0.0,
                                                        max_tokens=3))
        for _ in range(4):
            engine.step()
        # Deferred: still queued, never admitted, never errored.
        assert not r2.done
        assert engine.num_active == 0
        deferred = engine.stats["hbm_deferred_admissions"]
        assert deferred >= 4

        # Pressure relieved -> the queued request completes normally.
        # The degraded mode was latency, never a client-visible error.
        balloon.deflate()
        while engine.has_work:
            engine.step()
        assert r2.done and r2.finish_reason == "length"
        assert len(r2.output_token_ids) == 3
    finally:
        balloon.deflate()
        ledger.set_capacity(0)  # leave the module fixture un-gated


# ----------------------------------------------------------------------
# OOM forensics: engine dump (reason="oom" + memory.json)
# ----------------------------------------------------------------------

def test_engine_oom_leaves_memory_dump(engine, tmp_path, monkeypatch):
    rec = FlightRecorder(str(tmp_path), tracer=SpanTracer())
    rec.add_memory_source(engine.memledger.to_dict)
    install_recorder(rec)
    try:
        def boom():
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: out of memory while allocating "
                "decode buffers")
        monkeypatch.setattr(engine, "_admit", boom)
        with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
            engine.step()
    finally:
        install_recorder(None)
    dumps = list_dumps(str(tmp_path))
    assert len(dumps) == 1
    data = load_dump(dumps[0])
    assert data["context.json"]["reason"] == "oom"
    assert data["context.json"]["where"] == "engine_step"
    mem = data["memory.json"]
    assert mem["owners"]["params"]["bytes"] > 0
    assert sum(mem["buckets"].values()) == mem["bytes_in_use"]


# ----------------------------------------------------------------------
# Live Trainer: steplog fields, conservation, hbm-squeeze OOM drill
# ----------------------------------------------------------------------

def _train_batches(n=6):
    rng = np.random.default_rng(0)
    ids = [rng.integers(1, 500, (1, 2, 16), dtype=np.int32)
           for _ in range(n)]
    return [{"input_ids": a, "labels": a} for a in ids]


def _train_cfg(tmp, max_steps, fault="", budget=0, flight_dir=""):
    return Config(
        model=CFG, lora=LoRAConfig(enabled=False),
        data=DataConfig(max_seq_len=16),
        checkpoint=CheckpointConfig(save_strategy="no"),
        train=TrainConfig(num_epochs=1, micro_batch_size=2,
                          grad_accum_steps=1, max_steps=max_steps,
                          logging_steps=100, fault_inject_step=fault),
        telemetry=TelemetryConfig(
            step_log_path=str(tmp / "steps.jsonl"),
            hbm_budget_bytes=budget,
            flight_recorder=FlightRecorderConfig(dir=flight_dir)),
    )


def test_trainer_steplog_hbm_fields_and_conservation(tmp_path):
    from dlti_tpu.training import Trainer

    budget = 1 << 40  # 1 TiB: guaranteed headroom on a CI host
    trainer = Trainer(_train_cfg(tmp_path, max_steps=2, budget=budget))
    trainer.train(batches_per_epoch=_train_batches())

    rows = [json.loads(line) for line in open(tmp_path / "steps.jsonl")]
    steps = [r for r in rows if r.get("type") == "step"]
    assert len(steps) == 2
    for r in steps:
        assert r["hbm_bytes_in_use"] > 0
        assert 0 < r["hbm_headroom_bytes"] <= budget

    # The run's ledger still holds the final state: owners attributed,
    # buckets conserved on the live training process.
    snap = trainer._memledger.snapshot()
    assert snap["owners"]["params"]["bytes"] > 0
    assert snap["owners"]["optimizer_state"]["bytes"] > 0
    _assert_conserved(snap)
    # train() uninstalled the process-wide ledger on the way out.
    assert ml.get_ledger() is not trainer._memledger


def test_trainer_steplog_headroom_sentinel_without_budget(tmp_path):
    from dlti_tpu.training import Trainer

    Trainer(_train_cfg(tmp_path, max_steps=1)).train(
        batches_per_epoch=_train_batches())
    rows = [json.loads(line) for line in open(tmp_path / "steps.jsonl")]
    steps = [r for r in rows if r.get("type") == "step"]
    # CPU, no budget: capacity unknown -> -1 sentinel, never a fake 0.
    assert steps[0]["hbm_headroom_bytes"] == -1
    assert steps[0]["hbm_bytes_in_use"] > 0


def test_training_hbm_squeeze_dump_and_postmortem(tmp_path, monkeypatch):
    from dlti_tpu.training import Trainer

    monkeypatch.setenv("DLTI_CHAOS_BALLOON_BYTES", str(4 << 20))
    flight = tmp_path / "flight"
    cfg = _train_cfg(tmp_path, max_steps=4, fault="2:hbm-squeeze",
                     flight_dir=str(flight))
    try:
        with pytest.raises(TrainFault, match="RESOURCE_EXHAUSTED"):
            Trainer(cfg).train(batches_per_epoch=_train_batches())
    finally:
        configure_tracer(enabled=False)
        get_tracer().clear()

    dumps = list_dumps(str(flight))
    assert dumps, "hbm-squeeze left no flight dump"
    data = load_dump(dumps[-1])
    assert data["context.json"]["reason"] == "chaos_hbm-squeeze"
    mem = data["memory.json"]
    # The balloon was still live at dump time: the black box names the
    # squeezer and conserves the total.
    assert mem["owners"]["chaos_balloon"]["bytes"] >= 4 << 20
    assert mem["owners"]["params"]["bytes"] > 0
    assert sum(mem["buckets"].values()) == mem["bytes_in_use"]

    # postmortem renders "where the memory went" from the same dump.
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         dumps[-1]],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr[-1000:]
    assert "where the memory went" in r.stdout
    assert "chaos_balloon" in r.stdout
    rj = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "postmortem.py"),
         dumps[-1], "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert rj.returncode == 0, rj.stderr[-1000:]
    summary = json.loads(rj.stdout)
    assert summary["memory"]["buckets"]
    assert summary["memory"]["buckets"]["chaos_balloon"] >= 4 << 20
    assert summary["memory"]["source"] in ("device", "live_arrays")


# ----------------------------------------------------------------------
# Planner vs measured: scripts/memory_plan.py cross-check
# ----------------------------------------------------------------------

def test_memory_plan_training_matches_measured_params(tiny_params):
    plan = memory_plan.plan_training(CFG, param_dtype="float32")
    measured = tree_nbytes(tiny_params)
    # The analytic count tracks the real init to within 10% on the tiny
    # preset (norm scales et al. are the only unmodeled leaves).
    assert abs(plan["owners"]["params"] - measured) / measured < 0.10
    assert plan["owners"]["optimizer_state"] == 2 * plan["trainable_params"] * 4
    # A budget verdict that can't be wrong by construction.
    p2 = memory_plan.plan_training(CFG, param_dtype="float32",
                                   budget_bytes=plan["total_bytes"] + 1)
    assert p2["fits"] and p2["headroom_bytes"] == 1


def test_memory_plan_serving_matches_measured_kv_pool(engine):
    ec = engine.cfg
    plan = memory_plan.plan_serving(
        CFG, param_dtype="float32", kv_dtype="float32",
        num_blocks=ec.num_blocks, block_size=ec.block_size,
        max_model_len=ec.max_model_len)
    snap = engine.memledger.snapshot()
    measured_pool = snap["owners"]["kv_block_pool"]["bytes"] + \
        snap["owners"].get("prefix_cache_hbm", {}).get("bytes", 0)
    # The engine pre-allocates exactly the planned pool (fp32: payload
    # only, no quantization scales).
    assert plan["owners"]["kv_block_pool"] == measured_pool
    assert plan["kv_bytes_per_token"] == \
        2 * CFG.num_layers * CFG.num_kv_heads * CFG.resolved_head_dim * 4
    assert plan["max_resident_tokens"] == (ec.num_blocks - 1) * ec.block_size


def test_memory_plan_lora_trainable_count():
    n = memory_plan.lora_trainable_params(CFG, r=2)
    h, hd = CFG.hidden_size, CFG.resolved_head_dim
    q = 2 * (h + CFG.num_heads * hd)
    kv = 2 * (h + CFG.num_kv_heads * hd)
    o = 2 * (CFG.num_heads * hd + h)
    assert n == CFG.num_layers * (q + 2 * kv + o)


def test_memory_plan_cli_renders(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "memory_plan.py"),
         "--model", "llama_tiny", "--serving", "--num-blocks", "64",
         "--block-size", "8", "--kv-dtype", "float32",
         "--budget-gb", "1", "--json"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert r.returncode == 0, r.stderr[-1000:]
    p = json.loads(r.stdout)
    assert p["mode"] == "serving" and p["fits"]
    assert p["owners"]["kv_block_pool"] > 0
    assert p["max_blocks_in_budget"] >= 64
