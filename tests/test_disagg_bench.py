"""CI smoke for the disaggregation A/B microbench (satellite of the
prefill/decode disaggregation PR), mirroring
tests/test_prefix_tiering_bench.py: the artifact generator behind
``results/disagg_cpu.json`` must stay runnable, and its equivalence claim
must hold on a cold CPU run — outputs byte-identical between the
colocated and disaggregated arms, with real handoffs on the measured
path. The ≥25% TPOT headline is a property of the committed artifact
(3-run median on a quiet machine), not of this single noisy smoke run,
so the smoke pins shape + equivalence, not the margin."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks_dev", "disagg_ab.py")


@pytest.mark.slow
def test_disagg_ab_bench_smoke(tmp_path):
    out = tmp_path / "disagg_cpu.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # the bench sets its own device-count flag
    proc = subprocess.run(
        [sys.executable, BENCH, "--runs", "1", "--shorts", "12",
         "--longs", "3", "--max-tokens", "12", "--json-out", str(out)],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1500:]
    report = json.loads(out.read_text())

    # The equivalence claim is unconditional: the bench itself asserts it
    # before writing, and the report must record it.
    assert report["outputs_equal"] is True
    # Real migrations happened on the measured path.
    kh = report["kv_handoff"]
    assert kh["completed_total"] > 0
    assert kh["bytes_total"] > 0
    assert kh["latency_histogram"]["count"] == kh["completed_total"]
    # Report shape matches the committed artifact's schema.
    for key in ("benchmark", "platform", "workload", "arms",
                "decode_tpot_p99_ms", "decode_tpot_p99_improvement"):
        assert key in report, key
    assert set(report["arms"]) == {"colocated", "disagg"}
    for arm_runs in report["arms"].values():
        assert arm_runs and all(r["num_short_ok"] > 0 for r in arm_runs)


def test_committed_artifact_meets_the_bar():
    """The checked-in results/disagg_cpu.json is the PR's evidence; pin
    the acceptance bar so a regenerated artifact that misses it fails CI
    instead of silently shipping."""
    path = os.path.join(REPO, "results", "disagg_cpu.json")
    report = json.loads(open(path).read())
    assert report["outputs_equal"] is True
    assert report["decode_tpot_p99_improvement"] >= 0.25
    assert report["kv_handoff"]["completed_total"] > 0
