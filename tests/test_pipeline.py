"""Pipeline parallelism: layout roundtrip, forward equivalence vs the
unpipelined model, and a pipelined train step."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import (
    Config, DataConfig, LoRAConfig, ModelConfig, OptimizerConfig,
    ParallelConfig, TrainConfig,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.parallel.mesh import build_mesh
from dlti_tpu.parallel.pipeline import (
    from_pipeline_params,
    make_pipeline_train_step,
    pipeline_forward,
    pipeline_param_shardings,
    to_pipeline_params,
)
from dlti_tpu.training import build_optimizer, create_train_state

# Heavy jit-compile tier: excluded from the fast pre-commit gate
# (`pytest -m 'not slow'`); the full suite runs them.
pytestmark = pytest.mark.slow

CFG = ModelConfig(
    vocab_size=128, hidden_size=32, intermediate_size=64, num_layers=4,
    num_heads=2, num_kv_heads=2, max_seq_len=32, remat=False,
    dtype="float32", param_dtype="float32", attention_impl="reference",
)


@pytest.fixture(scope="module")
def pipe_mesh():
    return build_mesh(ParallelConfig(pipe=4))


@pytest.fixture(scope="module")
def model_and_params():
    model = LlamaForCausalLM(CFG, None)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return model, params


def test_param_layout_roundtrip(model_and_params):
    _, params = model_and_params
    pp = to_pipeline_params(params, CFG.num_layers)
    assert pp["layers"]["attn"]["q_proj"]["kernel"].shape[0] == CFG.num_layers
    back = from_pipeline_params(pp, CFG.num_layers)
    a = jax.tree_util.tree_leaves_with_path(params)
    b = jax.tree_util.tree_leaves_with_path(back)
    assert [p for p, _ in a] == [p for p, _ in b]
    for (_, x), (_, y) in zip(a, b):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_forward_matches_unpipelined(model_and_params, pipe_mesh):
    model, params = model_and_params
    ids = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, CFG.vocab_size)
    want, _ = model.apply({"params": params}, ids, deterministic=True)

    pp = to_pipeline_params(params, CFG.num_layers)
    sh = pipeline_param_shardings(pp, pipe_mesh)
    pp = jax.tree_util.tree_map(jax.device_put, pp, sh)
    got = pipeline_forward(pp, ids, CFG, pipe_mesh, num_microbatches=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_forward_microbatch_count_invariance(model_and_params, pipe_mesh):
    _, params = model_and_params
    ids = jax.random.randint(jax.random.PRNGKey(2), (8, 16), 0, CFG.vocab_size)
    pp = to_pipeline_params(params, CFG.num_layers)
    a = pipeline_forward(pp, ids, CFG, pipe_mesh, num_microbatches=2)
    b = pipeline_forward(pp, ids, CFG, pipe_mesh, num_microbatches=8)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


def test_rejects_bad_divisibility(model_and_params, pipe_mesh):
    _, params = model_and_params
    pp = to_pipeline_params(params, CFG.num_layers)
    ids = jnp.zeros((6, 8), jnp.int32)
    with pytest.raises(ValueError, match="divide"):
        pipeline_forward(pp, ids, CFG, pipe_mesh, num_microbatches=4)
    import dataclasses

    bad_cfg = dataclasses.replace(CFG, num_layers=3)
    with pytest.raises(ValueError, match="stages"):
        pipeline_forward(pp, jnp.zeros((4, 8), jnp.int32), bad_cfg, pipe_mesh)


def test_trainer_pipe_e2e_train_resume(tmp_path):
    """The production path (VERDICT r02 weak #2): Trainer with
    parallel.pipe=2 trains, checkpoints the stacked layout, resumes, and
    evals — no direct make_pipeline_train_step calls."""
    from dlti_tpu.config import CheckpointConfig
    from dlti_tpu.data import ByteTokenizer, make_batches
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=CFG,
        lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(pipe=2),
        data=DataConfig(max_seq_len=32, tokenizer="byte"),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_steps=2, save_total_limit=2,
                                    async_save=False),
        train=TrainConfig(num_epochs=1, micro_batch_size=4,
                          grad_accum_steps=2, max_steps=4,
                          logging_steps=100, eval_steps=4,
                          metrics_csv=str(tmp_path / "m.csv")),
    )
    texts = [f"sample {i} text {i * 7}" for i in range(160)]
    ds = make_batches(texts, ByteTokenizer(), seq_len=32, micro_batch_size=4,
                      grad_accum_steps=2, shard_by_host=False)
    state, record = Trainer(cfg).train(dataset=ds, eval_dataset=ds)
    assert np.isfinite(record.final_loss)
    assert np.isfinite(record.eval_loss)
    # Params really are in stacked pipeline layout.
    assert state.params["layers"]["attn"]["q_proj"]["kernel"].shape[0] == (
        CFG.num_layers)

    # Resume from the stacked checkpoint and take two more steps.
    cfg2 = cfg.replace(train=dataclasses_replace(cfg.train, max_steps=6))
    state2, _ = Trainer(cfg2).train(dataset=ds)
    assert int(state2.step) == 6


def dataclasses_replace(obj, **kw):
    import dataclasses

    return dataclasses.replace(obj, **kw)


def test_trainer_rejects_illegal_pipe_compositions():
    from dlti_tpu.config import ZeROStage
    from dlti_tpu.training.trainer import Trainer

    # SP composes with pipe, but not together with loss_chunk (the chunk
    # reshape regathers the sequence-sharded hidden — flat-path parity).
    bad = Config(
        model=CFG, lora=LoRAConfig(r=2, alpha=4),
        parallel=ParallelConfig(pipe=2, sequence=2),
        train=TrainConfig(loss_chunk=8),
    )
    with pytest.raises(ValueError, match="does not compose"):
        Trainer(bad)
    # fsdp axis without ZeRO-3 carries nothing — rejected loudly.
    bad2 = Config(
        model=CFG, lora=LoRAConfig(r=2, alpha=4),
        parallel=ParallelConfig(pipe=2, fsdp=2),
    )
    with pytest.raises(ValueError, match="does not compose"):
        Trainer(bad2)
    # Param offload needs LoRA (it offloads the frozen base; full
    # fine-tune has none) — rejected without it, legal with it.
    bad3 = Config(
        model=CFG, lora=LoRAConfig(enabled=False),
        parallel=ParallelConfig(pipe=2, data=2, offload_params=True),
    )
    with pytest.raises(ValueError, match="does not compose"):
        Trainer(bad3)


def test_pipeline_train_step_matches_single_device(pipe_mesh):
    """Loss and updated LoRA params from the pipelined step equal the plain
    single-device step on the same batch (GPipe == grad accumulation)."""
    from dlti_tpu.training.step import make_train_step

    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(CFG, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=True)
    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }

    # Reference: unpipelined step, accum dim of 1.
    ref_step = jax.jit(make_train_step(model, accum_steps=1))
    ref_batch = {k: v[None] for k, v in batch_flat.items()}
    rng = jax.random.PRNGKey(4)
    ref_state, ref_m = ref_step(state, ref_batch, rng)

    # Pipelined: same params in pipeline layout. Dropout is 0 so the rng
    # path difference does not matter.
    cfg = Config(model=CFG, lora=lora, optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=ParallelConfig(pipe=4), data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=8, grad_accum_steps=1))
    from dlti_tpu.parallel.pipeline import to_pipeline_state

    pstate = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                lora_enabled=True)
    pstate = to_pipeline_state(pstate, CFG.num_layers)
    pstep = make_pipeline_train_step(cfg, tx, pipe_mesh, num_microbatches=4)
    pstate, pm = pstep(pstate, batch_flat, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, CFG.num_layers)
    got = np.asarray(back["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    want = np.asarray(
        ref_state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipeline_steps_per_sync_matches(tmp_path):
    """steps_per_sync composes with the GPipe Trainer path: a scanned
    2-step window reproduces the per-step pipelined trajectory."""
    from dlti_tpu.config import CheckpointConfig, MODEL_PRESETS
    from dlti_tpu.training.trainer import Trainer

    rng = jax.random.PRNGKey(0)

    def run(k):
        cfg = Config(
            model=MODEL_PRESETS["llama_tiny"],
            lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
            optimizer=OptimizerConfig(warmup_steps=1),
            parallel=ParallelConfig(pipe=2),
            data=DataConfig(max_seq_len=16),
            train=TrainConfig(num_epochs=1, micro_batch_size=2,
                              grad_accum_steps=8, logging_steps=100,
                              steps_per_sync=k,
                              metrics_csv=str(tmp_path / f"mp{k}.csv")),
            checkpoint=CheckpointConfig(save_strategy="no"),
        )
        batches = [
            {"input_ids": np.asarray(jax.random.randint(
                jax.random.fold_in(rng, i), (8, 2, 16), 0,
                cfg.model.vocab_size)),
             "loss_mask": np.ones((8, 2, 16), np.int32)}
            for i in range(4)]
        t = Trainer(cfg)
        state, rec = t.train(batches_per_epoch=batches,
                             state=t.init_state(jax.random.fold_in(rng, 99)))
        return state, rec

    s1, r1 = run(1)
    s2, r2 = run(2)
    assert int(jax.device_get(s1.step)) == int(jax.device_get(s2.step)) == 4
    np.testing.assert_allclose(r1.final_loss, r2.final_loss, rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-6, rtol=1e-5)


def _run_pipe_vs_single_device(par, extra_checks=None):
    """Shared harness for the PP-composition equivalence family: run the
    single-device reference step and the pipelined step on ``par``'s
    mesh with identical init/batch/rng, assert equal loss and updated
    LoRA params. ``extra_checks(sh, pstate)`` runs after placement (for
    spec and physical-shard assertions). Sharded optimizer state goes
    through the production ``opt_state_shardings`` whenever ``par`` has
    a ZeRO stage, so the composition exercises the real opt layout."""
    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.parallel.sharding import opt_state_shardings
    from dlti_tpu.training.step import make_train_step

    mesh = build_mesh(par)
    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(CFG, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=True)
    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    ref_step = jax.jit(make_train_step(model, accum_steps=1))
    ref_batch = {k: v[None] for k, v in batch_flat.items()}
    rng = jax.random.PRNGKey(4)
    ref_state, ref_m = ref_step(state, ref_batch, rng)

    cfg = Config(model=CFG, lora=lora,
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=par,
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=8, grad_accum_steps=1))
    pstate = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                lora_enabled=True)
    pstate = to_pipeline_state(pstate, CFG.num_layers)
    sh = pipeline_param_shardings(pstate.params, mesh)
    replace = {"params": jax.tree_util.tree_map(
        jax.device_put, pstate.params, sh)}
    if int(par.zero_stage):
        replace["opt_state"] = jax.device_put(
            pstate.opt_state, opt_state_shardings(pstate.opt_state, cfg,
                                                  mesh))
    pstate = pstate.replace(**replace)
    if extra_checks is not None:
        extra_checks(sh, pstate)
    pstep = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=4)
    pstate, pm = pstep(pstate, batch_flat, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, CFG.num_layers)
    for layer in (0, CFG.num_layers - 1):
        got = np.asarray(
            back["model"][f"layers_{layer}"]["attn"]["q_proj"]["lora_b"])
        want = np.asarray(
            ref_state.params["model"][f"layers_{layer}"]["attn"]["q_proj"]["lora_b"])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def _assert_physically_sharded(leaf, spec, axis, factor=2):
    """The dim carrying ``axis`` in ``spec`` is really split ``factor``
    ways across the leaf's addressable shards."""
    d = spec.index(axis)
    assert all(s.data.shape[d] == leaf.shape[d] // factor
               for s in leaf.addressable_shards), (
        axis, [s.data.shape for s in leaf.addressable_shards])


def test_pipe_x_tensor_matches_single_device():
    """PP x TP (VERDICT r03 #8): pipe=2 x tensor=2 — stage-internal tensor
    sharding over a ('pipe','tensor') mesh, 'tensor' riding GSPMD inside
    the pipeline's shard_map — reproduces the single-device step: same
    loss, same updated LoRA params."""
    def checks(sh, pstate):
        # TP placement really happened: a q_proj kernel leaf must be
        # sharded over 'tensor' on its out dim (dim 2 with the leading
        # layer dim), and physically split.
        q_spec = sh["layers"]["attn"]["q_proj"]["kernel"].spec
        assert q_spec == jax.sharding.PartitionSpec("pipe", None, "tensor"), \
            q_spec
        _assert_physically_sharded(
            pstate.params["layers"]["attn"]["q_proj"]["kernel"], q_spec,
            "tensor")

    _run_pipe_vs_single_device(ParallelConfig(pipe=2, tensor=2), checks)


def test_pipe_x_zero3_matches_single_device(monkeypatch):
    """PP x ZeRO-3 (VERDICT r04 #4): pipe=2 x fsdp=2 — stacked leaves
    shard over 'fsdp' on a non-layer dim, 'fsdp' riding GSPMD as an auto
    axis inside the pipe shard_map (per-tick all-gather at use,
    reduce-scatter grads) — reproduces the single-device step: same
    loss, same updated LoRA params. The fsdp placement is asserted real
    (the fsdp-sharded dim physically halved)."""
    import dlti_tpu.parallel.sharding as sh_mod
    from dlti_tpu.config import ZeROStage

    # llama_tiny-scale dims sit under the production FSDP size floor;
    # lower it so placement actually happens in this test.
    monkeypatch.setattr(sh_mod, "_MIN_FSDP_DIM", 8)

    def checks(sh, pstate):
        q_spec = sh["layers"]["attn"]["q_proj"]["kernel"].spec
        assert q_spec[0] == "pipe" and "fsdp" in q_spec, q_spec
        _assert_physically_sharded(
            pstate.params["layers"]["attn"]["q_proj"]["kernel"], q_spec,
            "fsdp")

    _run_pipe_vs_single_device(
        ParallelConfig(pipe=2, fsdp=2, zero_stage=ZeROStage.ZERO3), checks)


@pytest.mark.parametrize("family,overrides", [
    ("mistral", dict(sliding_window=6)),
    ("qwen2", dict(attention_bias=True)),
    ("gemma", dict(tie_embeddings=True, mlp_activation="gelu_tanh",
                   rmsnorm_offset=True, embedding_scale=True)),
])
def test_pipeline_forward_model_families(pipe_mesh, family, overrides):
    """Every family switch rides the pipelined stage body unchanged:
    Mistral's sliding window, Qwen2's qkv bias, Gemma's (1+w) RMSNorm +
    scaled/tied embeddings + gelu MLP — pipelined logits equal the
    unpipelined model's."""
    import dataclasses

    fam_cfg = dataclasses.replace(CFG, **overrides)
    model = LlamaForCausalLM(fam_cfg, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ids = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0,
                             fam_cfg.vocab_size)
    want, _ = model.apply({"params": params}, ids, deterministic=True)
    pp = to_pipeline_params(params, fam_cfg.num_layers)
    got = pipeline_forward(pp, ids, fam_cfg, pipe_mesh, num_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5,
                               err_msg=f"{family} pipelined forward diverged")


def test_pipeline_flash_attention_matches_unpipelined(pipe_mesh):
    """The Pallas flash path runs INSIDE pipe stages (production config
    on chip: attention_impl auto -> flash): the kernels' out_shape now
    carries the enclosing shard_map's varying-manual-axes, without which
    tracing fails ("vma must not be None") — a latent chip bug for any
    PP run with flash. Interpret mode on CPU; logits equal the
    unpipelined flash model."""
    import dataclasses

    flash_cfg = dataclasses.replace(CFG, attention_impl="flash",
                                    flash_block_q=16, flash_block_kv=16)
    model = LlamaForCausalLM(flash_cfg, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    ids = jax.random.randint(jax.random.PRNGKey(3), (4, 16), 0,
                             flash_cfg.vocab_size)
    want, _ = model.apply({"params": params}, ids, deterministic=True)
    pp = to_pipeline_params(params, flash_cfg.num_layers)
    got = pipeline_forward(pp, ids, flash_cfg, pipe_mesh,
                           num_microbatches=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_packed_matches_unpipelined(pipe_mesh):
    """Packed batches under PP: segment ids and per-doc positions ride
    each microbatch through the stages, so the pipelined step reproduces
    the unpipelined packed step exactly."""
    from conftest import make_packed_segments
    from dlti_tpu.data.pipeline import packed_loss_mask, packed_positions
    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.training.step import make_train_step

    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(CFG, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=True)
    segs = make_packed_segments(8, 16)
    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "segment_ids": segs,
        "positions": packed_positions(segs),
        "loss_mask": packed_loss_mask(segs),
    }
    ref_step = jax.jit(make_train_step(model, accum_steps=1))
    ref_batch = {k: v[None] for k, v in batch_flat.items()}
    rng = jax.random.PRNGKey(4)
    ref_state, ref_m = ref_step(state, ref_batch, rng)

    cfg = Config(model=CFG, lora=lora,
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=ParallelConfig(pipe=4),
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=8, grad_accum_steps=1))
    pstate = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                lora_enabled=True)
    pstate = to_pipeline_state(pstate, CFG.num_layers)
    pstep = make_pipeline_train_step(cfg, tx, pipe_mesh, num_microbatches=4)
    pstate, pm = pstep(pstate, batch_flat, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, CFG.num_layers)
    got = np.asarray(back["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    want = np.asarray(
        ref_state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipeline_int8_frozen_base_matches_unpipelined(pipe_mesh, monkeypatch):
    """int8 frozen base under PP: the stage body dequantizes stacked
    {q, scale} leaves like the unpipelined block, and embed/head
    dequantize on the fly — the pipelined step reproduces the
    unpipelined int8 step."""
    import dlti_tpu.models.quantization as qmod
    from dlti_tpu.models.quantization import quantize_params_int8
    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.training.step import make_train_step

    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(CFG, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))

    # llama_tiny block kernels (64x64) sit under the production size
    # floor; lower it so the scanned stage body sees stacked int8 leaves.
    monkeypatch.setattr(qmod, "_MIN_QUANT_SIZE", 1 << 6)

    def fresh_state():
        st = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                lora_enabled=True)
        return st.replace(params=quantize_params_int8(st.params))

    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    state = fresh_state()
    # The stage body must see int8 leaves: assert a block kernel was
    # actually quantized (size floor lowered above).
    from dlti_tpu.models.quantization import is_quant_node
    assert is_quant_node(
        state.params["model"]["layers_0"]["attn"]["q_proj"]["kernel"])
    assert is_quant_node(state.params["model"]["embed_tokens"])
    ref_step = jax.jit(make_train_step(model, accum_steps=1))
    ref_batch = {k: v[None] for k, v in batch_flat.items()}
    rng = jax.random.PRNGKey(4)
    ref_state, ref_m = ref_step(state, ref_batch, rng)

    cfg = Config(model=CFG, lora=lora,
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=ParallelConfig(pipe=4),
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=8, grad_accum_steps=1,
                                   quantize_frozen_base="int8"))
    pstate = to_pipeline_state(fresh_state(), CFG.num_layers)
    pstep = make_pipeline_train_step(cfg, tx, pipe_mesh, num_microbatches=4)
    pstate, pm = pstep(pstate, batch_flat, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, CFG.num_layers)
    got = np.asarray(back["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    want = np.asarray(
        ref_state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipe_x_data_x_tensor_3d_matches_single_device():
    """Full 3D parallelism: pipe=2 x data=2 x tensor=2 over the 8-device
    mesh — GPipe stages manual over 'pipe', stage-internal TP and
    batch-row DP riding GSPMD as auto axes — reproduces the single-device
    step: same loss, same updated params."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.training.step import make_train_step

    mesh = build_mesh(ParallelConfig(pipe=2, data=2, tensor=2))
    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(CFG, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=True)
    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    ref_step = jax.jit(make_train_step(model, accum_steps=1))
    ref_batch = {k: v[None] for k, v in batch_flat.items()}
    rng = jax.random.PRNGKey(4)
    ref_state, ref_m = ref_step(state, ref_batch, rng)

    cfg = Config(model=CFG, lora=lora,
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=ParallelConfig(pipe=2, data=2, tensor=2),
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=8, grad_accum_steps=1))
    pstate = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                lora_enabled=True)
    pstate = to_pipeline_state(pstate, CFG.num_layers)
    sh = pipeline_param_shardings(pstate.params, mesh)
    pstate = pstate.replace(
        params=jax.tree_util.tree_map(jax.device_put, pstate.params, sh))
    sharded_batch = {
        k: jax.device_put(v, NamedSharding(mesh, P("data", None)))
        for k, v in batch_flat.items()}
    pstep = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=4)
    pstate, pm = pstep(pstate, sharded_batch, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, CFG.num_layers)
    for layer in (0, CFG.num_layers - 1):
        got = np.asarray(
            back["model"][f"layers_{layer}"]["attn"]["q_proj"]["lora_b"])
        want = np.asarray(
            ref_state.params["model"][f"layers_{layer}"]["attn"]["q_proj"]["lora_b"])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipeline_fp16_scaler_matches_flat_step(pipe_mesh):
    """fp16 dynamic loss scaling under PP: the pipelined step scales the
    loss, unscales grads, and evolves the scaler exactly like the flat
    step (same loss, same updated params, same scale metrics); a forced
    overflow skips the update and burns hysteresis identically."""
    import dataclasses

    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.training.step import make_train_step

    cfg16 = dataclasses.replace(CFG)  # fp32 compute keeps parity exact
    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(cfg16, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))

    def fresh(scale):
        return create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                  lora_enabled=True,
                                  fp16_initial_scale=scale)

    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        cfg16.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    rng = jax.random.PRNGKey(4)
    cfg = Config(model=cfg16, lora=lora,
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=ParallelConfig(pipe=4),
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=8, grad_accum_steps=1,
                                   fp16=True))
    pstep = make_pipeline_train_step(cfg, tx, pipe_mesh, num_microbatches=4)

    # Normal step: parity with the flat fp16 step.
    ref_step = jax.jit(make_train_step(model, accum_steps=1))
    ref_state, ref_m = ref_step(fresh(2.0 ** 4),
                                {k: v[None] for k, v in batch_flat.items()},
                                rng)
    pstate = to_pipeline_state(fresh(2.0 ** 4), cfg16.num_layers)
    pstate, pm = pstep(pstate, batch_flat, rng)
    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    assert float(pm["loss_scale"]) == float(ref_m["loss_scale"]) == 16.0
    assert float(pm["overflow"]) == 0.0
    back = from_pipeline_params(pstate.params, cfg16.num_layers)
    np.testing.assert_allclose(
        np.asarray(back["model"]["layers_0"]["attn"]["q_proj"]["lora_b"]),
        np.asarray(
            ref_state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"]),
        rtol=1e-4, atol=1e-6)

    # Forced overflow (NaN-poisoned LoRA factor, the flat fp16 test's
    # trigger): update skipped, hysteresis burned, params unchanged.
    st2 = fresh(2.0 ** 8)
    params = st2.params
    params["model"]["layers_0"]["attn"]["q_proj"]["lora_a"] = (
        params["model"]["layers_0"]["attn"]["q_proj"]["lora_a"]
        .at[0, 0].set(jnp.nan))
    pstate2 = to_pipeline_state(st2.replace(params=params), cfg16.num_layers)
    before = np.asarray(jax.device_get(
        pstate2.params["layers"]["attn"]["q_proj"]["lora_b"]))
    pstate2, pm2 = pstep(pstate2, batch_flat, rng)
    assert float(pm2["overflow"]) == 1.0
    assert int(pstate2.scaler["hysteresis_left"]) == 1
    assert float(pstate2.scaler["scale"]) == 256.0  # hysteresis absorbed it
    after = np.asarray(jax.device_get(
        pstate2.params["layers"]["attn"]["q_proj"]["lora_b"]))
    np.testing.assert_array_equal(before, after)
    # Second overflow exhausts hysteresis -> the scale actually halves
    # (catches transposed scale_window/hysteresis plumbing at the
    # pipeline call site).
    pstate2, pm3 = pstep(pstate2, batch_flat, rng)
    assert float(pm3["overflow"]) == 1.0
    assert float(pstate2.scaler["scale"]) == 128.0


def test_pipeline_loss_chunk_matches_unchunked(pipe_mesh):
    """Sequence-chunked CE under PP: the pipelined chunked step (hidden
    states + per-chunk head) reproduces the pipelined full-logits step."""
    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(CFG, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))

    def fresh():
        from dlti_tpu.parallel.pipeline import to_pipeline_state

        st = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                lora_enabled=True)
        return to_pipeline_state(st, CFG.num_layers)

    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    rng = jax.random.PRNGKey(4)

    def run(chunk):
        cfg = Config(model=CFG, lora=lora,
                     optimizer=OptimizerConfig(warmup_steps=0),
                     parallel=ParallelConfig(pipe=4),
                     data=DataConfig(max_seq_len=16),
                     train=TrainConfig(micro_batch_size=8,
                                       grad_accum_steps=1,
                                       loss_chunk=chunk))
        step = make_pipeline_train_step(cfg, tx, pipe_mesh,
                                        num_microbatches=4)
        return step(fresh(), batch_flat, rng)

    full_state, full_m = run(0)
    chunk_state, chunk_m = run(7)  # ragged chunk: exercises the padding

    np.testing.assert_allclose(float(chunk_m["loss"]), float(full_m["loss"]),
                               rtol=2e-6)
    a = jax.tree_util.tree_leaves(
        from_pipeline_params(chunk_state.params, CFG.num_layers))
    b = jax.tree_util.tree_leaves(
        from_pipeline_params(full_state.params, CFG.num_layers))
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-7)


def test_pipeline_zero1_shards_opt_state_same_losses(tmp_path):
    """ZeRO-1/2 x PP x DP: Adam moments shard over 'data' (ZeRO-2 adds
    the grad reduce-scatter pin) while the trajectory matches the
    replicated-optimizer pipe run exactly."""
    from dlti_tpu.config import CheckpointConfig, ZeROStage
    from dlti_tpu.data import ByteTokenizer, make_batches
    from dlti_tpu.training.trainer import Trainer

    def run(zero_stage, tag, offload=False, offload_p=False):
        cfg = Config(
            model=CFG,
            lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
            optimizer=OptimizerConfig(warmup_steps=2),
            parallel=ParallelConfig(pipe=2, data=2, zero_stage=zero_stage,
                                    offload_optimizer=offload,
                                    offload_params=offload_p),
            data=DataConfig(max_seq_len=32, tokenizer="byte"),
            checkpoint=CheckpointConfig(output_dir=str(tmp_path / tag),
                                        save_strategy="no"),
            train=TrainConfig(num_epochs=1, micro_batch_size=4,
                              grad_accum_steps=2, max_steps=4,
                              logging_steps=100,
                              # Offload runs also exercise the PP eval
                              # path (host params must be shimmed
                              # HBM-ward before the eval shard_map).
                              eval_steps=2 if offload_p else 0,
                              metrics_csv=str(tmp_path / f"{tag}.csv")),
        )
        texts = [f"sample {i} text {i * 7}" for i in range(160)]
        ds = make_batches(texts, ByteTokenizer(), seq_len=32,
                          micro_batch_size=4, grad_accum_steps=2,
                          shard_by_host=False)
        trainer = Trainer(cfg)
        state = trainer.init_state()
        sharded = 0
        on_host = 0
        for leaf in jax.tree_util.tree_leaves(state.opt_state):
            if hasattr(leaf, "addressable_shards") and leaf.ndim >= 1:
                if any(s.data.shape != leaf.shape
                       for s in leaf.addressable_shards):
                    sharded += 1
                if getattr(leaf.sharding, "memory_kind", None) == \
                        "pinned_host":
                    on_host += 1
        p_host = sum(
            1 for leaf in jax.tree_util.tree_leaves(state.params)
            if getattr(leaf.sharding, "memory_kind", None) == "pinned_host")
        state, record = trainer.train(
            dataset=ds, eval_dataset=ds if offload_p else None)
        return sharded, on_host, p_host, record.final_loss

    sharded0, host0, phost0, loss0 = run(ZeROStage.NONE, "base")
    sharded1, host1, phost1, loss1 = run(ZeROStage.ZERO1, "zero1")
    sharded2, host2, phost2, loss2 = run(ZeROStage.ZERO2, "zero2")
    assert sharded0 == 0, "baseline pipe run must replicate opt state"
    assert sharded1 > 0, "ZeRO-1 x PP must shard optimizer moments"
    assert sharded2 > 0, "ZeRO-2 x PP must shard optimizer moments"
    assert host0 == host1 == host2 == 0
    assert phost0 == phost1 == phost2 == 0
    np.testing.assert_allclose(loss1, loss0, rtol=1e-6)
    np.testing.assert_allclose(loss2, loss0, rtol=1e-6)
    # PP x host offload (r05, boundary-transfer mode): optimizer moments
    # AND the frozen base REST in pinned host memory (asserted
    # SEPARATELY so neither placement can silently regress), cross at
    # step boundaries, trajectory unchanged — with the eval pass
    # exercising the one-transfer-per-pass shim.
    shardedo, hosto, phosto, losso = run(ZeROStage.ZERO1, "zero1_offload",
                                         offload=True, offload_p=True)
    assert shardedo > 0
    assert hosto > 0, "offload_optimizer x PP must place moments on host"
    assert phosto > 0, "offload_params x PP must place frozen base on host"
    np.testing.assert_allclose(losso, loss0, rtol=1e-6)


@pytest.mark.parametrize("policy", ["nothing_saveable", "dots_saveable",
                                    "dots_with_no_batch_dims_saveable",
                                    "save_attn_out"])
def test_pipeline_remat_policy_matches_no_remat(pipe_mesh, policy):
    """Named remat policies under PP (r05): the scanned stage body
    passes cfg.remat_policy through the flat path's policy table —
    numerics identical to the no-remat pipelined step (remat never
    changes values, only what the backward recomputes)."""
    import dataclasses

    from dlti_tpu.parallel.pipeline import to_pipeline_state

    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))
    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    rng = jax.random.PRNGKey(4)

    def run(mc):
        model = LlamaForCausalLM(mc, lora)
        state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                   lora_enabled=True)
        cfg = Config(model=mc, lora=lora,
                     optimizer=OptimizerConfig(warmup_steps=0),
                     parallel=ParallelConfig(pipe=4),
                     data=DataConfig(max_seq_len=16),
                     train=TrainConfig(micro_batch_size=8,
                                       grad_accum_steps=1))
        pstate = to_pipeline_state(state, mc.num_layers)
        pstep = make_pipeline_train_step(cfg, tx, pipe_mesh,
                                         num_microbatches=4)
        pstate, pm = pstep(pstate, batch_flat, rng)
        back = from_pipeline_params(pstate.params, mc.num_layers)
        return float(pm["loss"]), np.asarray(
            back["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])

    base_loss, base_w = run(CFG)
    remat_loss, remat_w = run(
        dataclasses.replace(CFG, remat=True, remat_policy=policy))
    np.testing.assert_allclose(remat_loss, base_loss, rtol=1e-6)
    np.testing.assert_allclose(remat_w, base_w, rtol=1e-6, atol=1e-7)


def test_pipe_x_tensor_x_zero3_matches_single_device(monkeypatch):
    """The big three together — pipe=2 x tensor=2 x fsdp=2 (GPipe +
    stage-internal TP + ZeRO-3 param sharding, all 8 devices): stacked
    leaves carry P('pipe', 'fsdp', 'tensor'), BOTH inner axes physically
    split, optimizer state through the production ZeRO-3 layout, and the
    step reproduces the single-device step."""
    import dlti_tpu.parallel.sharding as sh_mod
    from dlti_tpu.config import ZeROStage

    monkeypatch.setattr(sh_mod, "_MIN_FSDP_DIM", 8)

    def checks(sh, pstate):
        q_spec = sh["layers"]["attn"]["q_proj"]["kernel"].spec
        assert (q_spec[0] == "pipe" and "tensor" in q_spec
                and "fsdp" in q_spec), q_spec
        leaf = pstate.params["layers"]["attn"]["q_proj"]["kernel"]
        _assert_physically_sharded(leaf, q_spec, "tensor")
        _assert_physically_sharded(leaf, q_spec, "fsdp")

    _run_pipe_vs_single_device(
        ParallelConfig(pipe=2, tensor=2, fsdp=2,
                       zero_stage=ZeROStage.ZERO3), checks)


def test_pipe_x_sequence_matches_single_device():
    """PP x SP (the last mesh axis): under the pipe shard_map, sequence
    parallelism delegates attention to GSPMD over the AUTO 'sequence'
    axis (all-gather-style SP; a nested manual ring either computes
    wrong gradients with check_vma=False or fails verification on this
    jax — see ring_attention's nested-delegation comment). Activations
    stay sequence-sharded via the batch pins; the pipelined train step
    reproduces the single-device step: same loss, same updated params.

    SGD, not Adam: partitioned-reduction grads differ from the flat step
    at epsilon scale, and Adam's first step (~ +/- lr * sign) amplifies
    that into sign flips on near-zero grads — a property of the
    optimizer, not an error. With SGD the param delta IS the grad
    (scaled), so the comparison is smooth."""
    import optax

    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.training.step import make_train_step

    par = ParallelConfig(pipe=2, sequence=2)
    mesh = build_mesh(par)
    assert mesh.shape["pipe"] == 2 and mesh.shape["sequence"] == 2

    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    tx = optax.sgd(0.1)
    model = LlamaForCausalLM(CFG, lora)  # ref: plain attention, no mesh
    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    ref_batch = {k: v[None] for k, v in batch_flat.items()}
    rng = jax.random.PRNGKey(4)
    state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                               lora_enabled=True)
    ref_step = jax.jit(make_train_step(model, accum_steps=1))
    ref_state, ref_m = ref_step(state, ref_batch, rng)

    cfg = Config(model=CFG, lora=lora,
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=par,
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=8, grad_accum_steps=1))
    pstate = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                lora_enabled=True)
    pstate = to_pipeline_state(pstate, CFG.num_layers)
    pstate = pstate.replace(params=jax.tree_util.tree_map(
        jax.device_put, pstate.params,
        pipeline_param_shardings(pstate.params, mesh)))
    pstep = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=4)
    pstate, pm = pstep(pstate, batch_flat, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, CFG.num_layers)
    for layer in (0, CFG.num_layers - 1):
        got = np.asarray(
            back["model"][f"layers_{layer}"]["attn"]["q_proj"]["lora_b"])
        want = np.asarray(
            ref_state.params["model"][f"layers_{layer}"]["attn"]["q_proj"]["lora_b"])
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipeline_remat_stride_matches_no_remat():
    """Selective remat under PP (r05): layers scan in groups of `stride`
    with every stride-th block keeping its activations — numerics equal
    the no-remat pipelined step (pipe=2 so layers_per_stage=2 divides
    stride=2)."""
    import dataclasses

    from dlti_tpu.parallel.pipeline import to_pipeline_state

    mesh = build_mesh(ParallelConfig(pipe=2))
    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))
    batch_flat = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (8, 16), 0,
                                        CFG.vocab_size),
        "loss_mask": jnp.ones((8, 16), jnp.int32),
    }
    rng = jax.random.PRNGKey(4)

    def run(mc):
        model = LlamaForCausalLM(mc, lora)
        state = create_train_state(jax.random.PRNGKey(0), model, tx, (4, 16),
                                   lora_enabled=True)
        cfg = Config(model=mc, lora=lora,
                     optimizer=OptimizerConfig(warmup_steps=0),
                     parallel=ParallelConfig(pipe=2),
                     data=DataConfig(max_seq_len=16),
                     train=TrainConfig(micro_batch_size=8,
                                       grad_accum_steps=1))
        pstate = to_pipeline_state(state, mc.num_layers)
        pstep = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=4)
        pstate, pm = pstep(pstate, batch_flat, rng)
        back = from_pipeline_params(pstate.params, mc.num_layers)
        return float(pm["loss"]), np.asarray(
            back["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])

    base_loss, base_w = run(CFG)
    strided_loss, strided_w = run(dataclasses.replace(
        CFG, remat=True, remat_policy="dots_saveable", remat_stride=2))
    np.testing.assert_allclose(strided_loss, base_loss, rtol=1e-6)
    np.testing.assert_allclose(strided_w, base_w, rtol=1e-6, atol=1e-7)


def test_pipe_x_expert_matches_flat():
    """PP x EP: stacked MoE expert weights shard over 'expert' on the
    expert dim inside the pipe shard_map (dispatch all-to-all via GSPMD
    auto axes) — reproduces the flat grad-accumulation MoE step: same
    CE, same aux, same updated params. FULL fine-tune (no LoRA), so the
    expert-sharded w1/w2/w3 actually receive gradients and optimizer
    updates through the sharded path, and the UPDATED expert weights are
    compared. Physical expert placement asserted (expert dim halved
    across shards)."""
    import dataclasses

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.training.step import make_train_step

    moe_cfg = dataclasses.replace(
        MODEL_PRESETS["mixtral_tiny"], num_layers=4, remat=False,
        dtype="float32", param_dtype="float32",
        attention_impl="reference", max_seq_len=32)
    model = LlamaForCausalLM(moe_cfg, None)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))

    def fresh():
        return create_train_state(jax.random.PRNGKey(0), model, tx, (2, 16),
                                  lora_enabled=False)

    batch = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (4, 2, 16),
                                        0, moe_cfg.vocab_size),
        "loss_mask": jnp.ones((4, 2, 16), jnp.int32),
    }
    rng = jax.random.PRNGKey(4)
    ref_step = jax.jit(make_train_step(model, accum_steps=4))
    ref_state, ref_m = ref_step(fresh(), batch, rng)

    par = ParallelConfig(pipe=2, expert=2)
    mesh = build_mesh(par)
    cfg = Config(model=moe_cfg, lora=LoRAConfig(enabled=False),
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=par,
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=2, grad_accum_steps=4))
    pstate = to_pipeline_state(fresh(), moe_cfg.num_layers)
    sh = pipeline_param_shardings(pstate.params, mesh)
    w1_spec = sh["layers"]["mlp"]["w1"].spec
    assert w1_spec[0] == "pipe" and w1_spec[1] == "expert", w1_spec
    pstate = pstate.replace(
        params=jax.tree_util.tree_map(jax.device_put, pstate.params, sh))
    w1 = pstate.params["layers"]["mlp"]["w1"]
    assert all(s.data.shape[1] == w1.shape[1] // 2
               for s in w1.addressable_shards), (
        f"expert sharding not physically placed: "
        f"{[s.data.shape for s in w1.addressable_shards]}")
    pstep = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=4)
    batch_flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in batch.items()}
    pstate, pm = pstep(pstate, batch_flat, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(pm["aux_loss"]), float(ref_m["aux_loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, moe_cfg.num_layers)
    # The expert weights themselves must have been UPDATED identically
    # through the expert-sharded pipe path (full FT: they are trainable).
    for layer in (0, moe_cfg.num_layers - 1):
        got = np.asarray(back["model"][f"layers_{layer}"]["mlp"]["w1"])
        want = np.asarray(
            ref_state.params["model"][f"layers_{layer}"]["mlp"]["w1"])
        assert not np.allclose(
            want, np.asarray(
                fresh().params["model"][f"layers_{layer}"]["mlp"]["w1"])), \
            "flat step did not update expert weights (test is vacuous)"
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_pipeline_moe_matches_flat_grad_accum():
    """MoE under PP: the pipelined step's aux-loss collection (per-layer
    sown losses, edge-tick masked, psum over pipe) reproduces the flat
    grad-accumulation step with identical microbatching — same CE, same
    aux, same updated params."""
    import dataclasses

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.parallel.pipeline import to_pipeline_state
    from dlti_tpu.training.step import make_train_step

    moe_cfg = dataclasses.replace(
        MODEL_PRESETS["mixtral_tiny"], num_layers=4, remat=False,
        dtype="float32", param_dtype="float32",
        attention_impl="reference", max_seq_len=32)
    lora = LoRAConfig(r=2, alpha=4, dropout=0.0)
    model = LlamaForCausalLM(moe_cfg, lora)
    tx = build_optimizer(OptimizerConfig(warmup_steps=0))

    def fresh():
        return create_train_state(jax.random.PRNGKey(0), model, tx, (2, 16),
                                  lora_enabled=True)

    # (accum=4, mb=2, seq=16): the flat step's microbatches == the
    # pipeline's microbatches, so even capacity DROPS match exactly.
    batch = {
        "input_ids": jax.random.randint(jax.random.PRNGKey(3), (4, 2, 16),
                                        0, moe_cfg.vocab_size),
        "loss_mask": jnp.ones((4, 2, 16), jnp.int32),
    }
    rng = jax.random.PRNGKey(4)
    ref_step = jax.jit(make_train_step(model, accum_steps=4))
    ref_state, ref_m = ref_step(fresh(), batch, rng)
    assert "aux_loss" in ref_m

    cfg = Config(model=moe_cfg, lora=lora,
                 optimizer=OptimizerConfig(warmup_steps=0),
                 parallel=ParallelConfig(pipe=4),
                 data=DataConfig(max_seq_len=16),
                 train=TrainConfig(micro_batch_size=2, grad_accum_steps=4))
    mesh = build_mesh(ParallelConfig(pipe=4))
    pstate = to_pipeline_state(fresh(), moe_cfg.num_layers)
    pstep = make_pipeline_train_step(cfg, tx, mesh, num_microbatches=4)
    batch_flat = {k: v.reshape((-1,) + v.shape[2:]) for k, v in batch.items()}
    pstate, pm = pstep(pstate, batch_flat, rng)

    np.testing.assert_allclose(float(pm["loss"]), float(ref_m["loss"]),
                               rtol=1e-5)
    np.testing.assert_allclose(float(pm["aux_loss"]), float(ref_m["aux_loss"]),
                               rtol=1e-5)
    back = from_pipeline_params(pstate.params, moe_cfg.num_layers)
    got = np.asarray(back["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    want = np.asarray(
        ref_state.params["model"]["layers_0"]["attn"]["q_proj"]["lora_b"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)
