"""Static guard: persistence writes in covered modules go through
``utils.durable_io``.

The durable writer's classified retry/reclaim/degrade policy (and its
``DLTI_IO_FAULT`` chaos hook) only protects writes that actually route
through it. This AST walk — the ``test_span_naming.py`` pattern — makes
that routing a *contract*: any write-mode ``open()`` or ``os.replace`` /
``os.rename`` added to a covered persistence module fails here unless it
is deliberately allowlisted (reads, subprocess log handles, and the
durable writer's own raw ops are the only legitimate exceptions).

The walk is an AST scan, not an import: a write behind a rarely-taken
error branch is still caught, and the guard costs no jax startup.
"""

import ast
import os

import pytest

PKG = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "dlti_tpu")

# The persistence modules the tentpole routes through durable_io (the
# module list from the durable_io docstring). chaos.py is deliberately
# NOT covered: its whole job is raw byte damage (bit flips, truncation)
# outside the durable path.
COVERED_MODULES = (
    os.path.join("checkpoint", "store.py"),
    os.path.join("serving", "adapters.py"),
    os.path.join("serving", "deploy.py"),
    os.path.join("serving", "fleet.py"),
    os.path.join("serving", "prefix_tiers.py"),
    os.path.join("telemetry", "flightrecorder.py"),
    os.path.join("telemetry", "steplog.py"),
    os.path.join("telemetry", "watchdog.py"),
    os.path.join("training", "elastic.py"),
    os.path.join("training", "sentinel.py"),
)

# (relpath, enclosing function) pairs allowed to touch the file boundary
# directly. Keyed by function name, not line number, so unrelated edits
# don't churn the allowlist.
_ALLOWED_RAW_WRITES = {
    # Supervisor worker stdout/stderr capture: long-lived subprocess log
    # handles passed to Popen — a stream, not a persistence write, and
    # it must not share the durable writer's retry/degrade machinery.
    (os.path.join("training", "elastic.py"), "_spawn"),
    # Fleet worker stdout/stderr capture: same shape — a long-lived
    # subprocess log handle handed to Popen, not a persistence write.
    (os.path.join("serving", "fleet.py"), "make_subprocess_spawner"),
}

_WRITE_MODE_CHARS = set("wax+")


def _module_calls(path):
    """Yield (lineno, enclosing function name, call node) for every call
    in ``path``."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    func_of = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for child in ast.walk(node):
                func_of.setdefault(id(child), node.name)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node.lineno, func_of.get(id(node), "<module>"), node


def _literal_mode(call):
    """The literal mode argument of an ``open()`` call, or None."""
    if len(call.args) > 1 and isinstance(call.args[1], ast.Constant):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def _raw_write_sites(rel):
    """(lineno, func, description) for raw write-boundary calls in a
    covered module: write-mode builtin ``open`` and ``os.replace`` /
    ``os.rename``."""
    sites = []
    for lineno, func, call in _module_calls(os.path.join(PKG, rel)):
        f = call.func
        if isinstance(f, ast.Name) and f.id == "open":
            mode = _literal_mode(call)
            if mode is None and (len(call.args) > 1 or any(
                    kw.arg == "mode" for kw in call.keywords)):
                # A computed mode can hide a write; flag it.
                sites.append((lineno, func, "open(mode=<non-literal>)"))
            elif mode and _WRITE_MODE_CHARS & set(str(mode)):
                sites.append((lineno, func, f"open(mode={mode!r})"))
        elif (isinstance(f, ast.Attribute)
              and f.attr in ("replace", "rename")
              and isinstance(f.value, ast.Name) and f.value.id == "os"):
            sites.append((lineno, func, f"os.{f.attr}"))
    return sites


def test_covered_modules_route_writes_through_durable_io():
    offenders = []
    for rel in COVERED_MODULES:
        for lineno, func, what in _raw_write_sites(rel):
            if (rel, func) in _ALLOWED_RAW_WRITES:
                continue
            offenders.append(f"dlti_tpu/{rel}:{lineno} ({func}): {what}")
    assert not offenders, (
        "raw write-boundary calls in durable-io-covered modules:\n  "
        + "\n  ".join(offenders)
        + "\nroute them through dlti_tpu.utils.durable_io (write_bytes / "
          "append_line / replace / write_json_atomic / LineWriter) so the "
          "classified retry/reclaim/degrade policy and the DLTI_IO_FAULT "
          "chaos hook apply, or allowlist deliberately")


def test_allowlist_entries_still_exist():
    """Every allowlisted site must still be a real raw-write site — a
    stale entry is a hole the guard thinks it has plugged."""
    for rel, func in _ALLOWED_RAW_WRITES:
        assert any(f == func for _, f, _w in _raw_write_sites(rel)), (
            f"allowlist entry ({rel}, {func}) matches no raw write site; "
            f"remove it")


def test_covered_modules_all_exist():
    for rel in COVERED_MODULES:
        assert os.path.isfile(os.path.join(PKG, rel)), rel


def test_walk_actually_sees_raw_writes():
    """Anti-vacuity: the scanner must flag the durable writer's own raw
    ops (the one module that legitimately touches the boundary) — an
    empty walk would pass the guard trivially."""
    rel = os.path.join("utils", "durable_io.py")
    sites = _raw_write_sites(rel)
    descs = {w for _, _f, w in sites}
    assert any("open(mode='wb')" in d for d in descs), sites
    assert any(d == "os.replace" for d in descs), sites


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-q"]))
