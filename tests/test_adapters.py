"""Multi-LoRA serving: adapter catalog, HBM pool, batched engine path.

Three layers, cheapest first:

* **Host-side units** — checkpoint format round-trip, catalog
  verification (corrupt ⇒ quarantine + unknown, so routing 404s),
  refcounted-LRU pool semantics, and the planner/pool/memledger
  byte-exact cross-check.
* **Tier-1 equivalence** (the acceptance pin): one shared-base engine
  serving a batch where every row wears a different adapter emits
  token-identical streams to per-adapter merged-weights engines —
  greedy AND seeded sampling, bf16 AND int8 base — and base requests
  stay byte-identical to an adapter-free engine.
* **Slow integration** — hot-register while the engine is mid-decode,
  replica-failover resubmit preserving each request's adapter, and the
  train → save → register → generate loop with no engine restart.
"""

import os
import sys
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax.core import unfreeze

from dlti_tpu.checkpoint.chaos import FaultyIO
from dlti_tpu.config import LoRAConfig, MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.models.lora import merge_lora_params
from dlti_tpu.serving import (
    EngineConfig, InferenceEngine, ReplicatedEngine, SamplingParams,
)
from dlti_tpu.serving import adapters as adapters_mod
from dlti_tpu.serving.adapters import (
    AdapterError,
    AdapterPool,
    extract_adapter_weights,
    get_catalog,
    plan_pool_bytes,
    register_adapter,
    save_adapter,
)
from dlti_tpu.utils import durable_io

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))
import memory_plan  # noqa: E402

CFG = MODEL_PRESETS["llama_tiny"]
TARGETS = ("q_proj", "k_proj", "v_proj", "o_proj")
R, ALPHA = 4, 8.0

PROMPTS = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8, 2, 8], [5, 5, 5, 5],
           [11, 12, 13]]
GREEDY = SamplingParams(temperature=0.0, max_tokens=12)
SEEDED = SamplingParams(temperature=0.8, seed=1234, max_tokens=12)


@pytest.fixture(autouse=True)
def _clean_catalog():
    """The catalog is process-global by design; keep tests hermetic."""
    get_catalog().clear()
    yield
    get_catalog().clear()


def _randomize_lora(tree, rng):
    # init leaves lora_b all-zero (delta == 0); give both factors real
    # values so the adapter visibly moves the logits.
    for k in tree:
        v = tree[k]
        if not isinstance(v, dict):
            continue
        if "lora_a" in v and "lora_b" in v:
            v["lora_a"] = jnp.asarray(
                rng.normal(0.0, 0.2, np.shape(v["lora_a"])), jnp.float32)
            v["lora_b"] = jnp.asarray(
                rng.normal(0.0, 0.2, np.shape(v["lora_b"])), jnp.float32)
        else:
            _randomize_lora(v, rng)


def _lora_params(seed):
    model = LlamaForCausalLM(CFG, LoRAConfig(r=R, alpha=int(ALPHA),
                                             dropout=0.0))
    p = unfreeze(model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 8), jnp.int32))["params"])
    _randomize_lora(p, np.random.RandomState(seed))
    return p


@pytest.fixture(scope="module")
def setup(tmp_path_factory):
    """Two distinct adapters over one shared base + their merged trees."""
    root = tmp_path_factory.mktemp("adapters")
    trees = {"ad-a": _lora_params(1), "ad-b": _lora_params(2)}
    # Same init key in both trees: the base kernels are identical; a
    # zero-scale merge strips the LoRA leaves without touching them.
    base = merge_lora_params(trees["ad-a"], scaling=0.0)
    dirs, merged = {}, {}
    for name, tree in trees.items():
        d = str(root / name)
        save_adapter(d, tree, alpha=ALPHA)
        dirs[name] = d
        merged[name] = merge_lora_params(tree, alpha=ALPHA)
    return types.SimpleNamespace(base=base, trees=trees, dirs=dirs,
                                 merged=merged)


def _ec(**kw):
    d = dict(max_seqs=4, block_size=8, num_blocks=64, max_model_len=64,
             cache_dtype="float32", eos_token_id=-1)
    d.update(kw)
    return EngineConfig(**d)


def _drain(eng, reqs):
    while eng.has_work:
        eng.step()
    return [eng._result(r) for r in reqs]


def _corrupt(directory):
    """Flip bytes in the largest data file so digest verification trips."""
    files = [os.path.join(directory, f) for f in os.listdir(directory)]
    target = max((f for f in files if os.path.isfile(f)), key=os.path.getsize)
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.seek(size // 2)
        chunk = f.read(64)
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))


def _bf16(tree):
    return jax.tree_util.tree_map(
        lambda x: x.astype(jnp.bfloat16)
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x, tree)


def _bf16_round_base(tree):
    """Base leaves rounded through bf16 back to f32 — the exact values a
    bf16-resident base contributes under f32 accumulation. LoRA factors
    stay untouched f32 masters (the pool holds them in f32 too)."""
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out[k] = _bf16_round_base(v)
        elif k in ("lora_a", "lora_b"):
            out[k] = v
        else:
            out[k] = jnp.asarray(v).astype(jnp.bfloat16).astype(jnp.float32)
    return out


def _row(pool, idx):
    return jax.tree_util.tree_map(lambda x: np.asarray(x[idx]), pool.tree)


# ----------------------------------------------------------------------
# Checkpoint format + catalog
# ----------------------------------------------------------------------

def test_extract_and_save_require_lora_factors(setup, tmp_path):
    weights = extract_adapter_weights(setup.trees["ad-a"])
    # Every targeted projection of every layer made it into the subtree.
    flat = adapters_mod._flatten_lora(weights)
    names = {p[-1] for p in flat}
    assert names == set(TARGETS)
    assert len(flat) == CFG.num_layers * len(TARGETS)
    # A plain (merged / base) tree has nothing to save.
    with pytest.raises(ValueError, match="no lora"):
        save_adapter(str(tmp_path / "empty"), setup.base)


def test_catalog_register_verifies_and_lists(setup):
    cat = get_catalog()
    assert register_adapter("ad-a", setup.dirs["ad-a"]) == "ad-a"
    register_adapter("ad-b", setup.dirs["ad-b"])
    assert cat.names() == ["ad-a", "ad-b"]
    assert "ad-a" in cat and "ghost" not in cat
    assert cat.directory("ad-a") == os.path.abspath(setup.dirs["ad-a"])
    assert cat.unregister("ad-a") and not cat.unregister("ad-a")
    assert cat.names() == ["ad-b"]
    # Unreadable directory never lands in the catalog.
    with pytest.raises(AdapterError, match="unreadable|corrupt"):
        register_adapter("nope", "/does/not/exist")
    assert "nope" not in cat


@pytest.mark.parametrize("bad", ["", "has space", "a/b", "a\\b", "a\nb"])
def test_catalog_rejects_bad_names(setup, bad):
    with pytest.raises(AdapterError, match="invalid adapter name"):
        register_adapter(bad, setup.dirs["ad-a"])


def test_corrupt_checkpoint_quarantined_at_registration(setup, tmp_path):
    d = str(tmp_path / "bad")
    save_adapter(d, setup.trees["ad-a"], alpha=ALPHA)
    _corrupt(d)
    with pytest.raises(AdapterError, match="corrupt"):
        register_adapter("bad", d)
    assert "bad" not in get_catalog()
    # Quarantined for forensics, not deleted: the dir moved aside.
    qdir = os.path.join(str(tmp_path), "_quarantine")
    assert not os.path.exists(d)
    assert os.path.isdir(qdir) and os.listdir(qdir)


def test_corrupt_after_registration_unregisters_on_load(setup, tmp_path):
    """Registration verified fine; the bytes rotted later. The pool load
    quarantines, raises the request-scoped error, and drops the name so
    the next request 404s at admission instead of retrying forever."""
    d = str(tmp_path / "rots")
    save_adapter(d, setup.trees["ad-a"], alpha=ALPHA)
    register_adapter("rots", d)
    _corrupt(d)
    pool = AdapterPool(setup.base, num_slots=2, rank=R, targets=TARGETS)
    with pytest.raises(AdapterError, match="corrupt"):
        pool.acquire("rots")
    assert "rots" not in get_catalog()
    assert not pool.resident("rots")
    with pytest.raises(AdapterError, match="unknown adapter"):
        pool.acquire("rots")


# ----------------------------------------------------------------------
# Storage faults during export (durable-writer integration)
# ----------------------------------------------------------------------

@pytest.fixture()
def _clean_io():
    durable_io.reset_for_tests()
    yield
    durable_io.reset_for_tests()


def test_save_adapter_torn_write_quarantines_and_reexport_serves(
        setup, tmp_path, _clean_io):
    """A torn write mid-export leaves NOTHING at the target path and no
    stray staging dir — the partial bytes are quarantined for forensics —
    and a re-export after the fault clears loads rows byte-identical to
    an unfaulted export of the same tree."""
    d = str(tmp_path / "ad-t")
    with FaultyIO.from_spec("*.bin:torn"):
        with pytest.raises(OSError):
            save_adapter(d, setup.trees["ad-a"], alpha=ALPHA)
    assert not os.path.exists(d)
    assert not [n for n in os.listdir(tmp_path) if ".tmp-" in n]
    qdir = os.path.join(str(tmp_path), "_quarantine")
    assert os.path.isdir(qdir) and os.listdir(qdir)
    assert durable_io.is_degraded("adapter")

    save_adapter(d, setup.trees["ad-a"], alpha=ALPHA)  # fault cleared
    assert not durable_io.is_degraded("adapter")       # success heals
    register_adapter("ad-t", d)
    register_adapter("ad-a", setup.dirs["ad-a"])
    pool = AdapterPool(setup.base, num_slots=2, rank=R, targets=TARGETS)
    row_t, _ = pool.acquire("ad-t")
    row_a, _ = pool.acquire("ad-a")
    jax.tree_util.tree_map(np.testing.assert_array_equal,
                           _row(pool, row_t), _row(pool, row_a))


def test_save_adapter_enospc_reclaims_quarantine_then_lands(
        setup, tmp_path, _clean_io):
    """ENOSPC mid-export: the reclaim pass quota-evicts the quarantined
    wreckage a previous failed save left behind, then the free retry
    lands the export whole (digest-verified at registration)."""
    with FaultyIO.from_spec("*.bin:torn"):
        with pytest.raises(OSError):
            save_adapter(str(tmp_path / "ad-bad"), setup.trees["ad-a"],
                         alpha=ALPHA)
    qdir = tmp_path / "_quarantine"
    assert list(qdir.iterdir())

    d = str(tmp_path / "ad-ok")
    with FaultyIO.from_spec("*.bin:ENOSPC:1"):
        save_adapter(d, setup.trees["ad-a"], alpha=ALPHA)
    assert not qdir.exists() or not list(qdir.iterdir())
    led = durable_io.disk_ledger()["adapter"]
    assert led["reclaims"] == 1 and led["reclaimed_bytes"] > 0
    register_adapter("ad-ok", d)  # digest verification: export is whole


# ----------------------------------------------------------------------
# Pool: plan / LRU / refcounts / compatibility
# ----------------------------------------------------------------------

def test_pool_bytes_match_planner_and_memory_plan(setup):
    pool = AdapterPool(setup.base, num_slots=3, rank=R, targets=TARGETS)
    want = plan_pool_bytes(CFG, TARGETS, R, 3)
    assert pool.nbytes == want
    assert memory_plan.adapter_pool_bytes(CFG, 3, R, TARGETS) == want
    assert memory_plan.adapter_pool_bytes(CFG, 0) == 0
    with pytest.raises(ValueError, match="unknown adapter target"):
        memory_plan.adapter_pool_bytes(CFG, 2, R, ("bogus",))


def test_engine_memledger_owner_matches_plan(setup):
    """The measured lora_adapters owner equals the paper plan, byte for
    byte (the kv_block_pool cross-check pattern)."""
    eng = InferenceEngine(CFG, setup.base,
                          _ec(adapter_slots=3, adapter_rank=R))
    snap = eng.memledger.snapshot()
    measured = snap["owners"]["lora_adapters"]["bytes"]
    assert measured == eng.adapter_pool.nbytes
    assert measured == memory_plan.adapter_pool_bytes(CFG, 3, R, TARGETS)
    plan = memory_plan.plan_serving(CFG, adapter_slots=3, adapter_rank=R,
                                    adapter_targets=TARGETS)
    assert plan["owners"]["lora_adapters"] == measured


def test_pool_load_evict_reload_byte_equality(setup, tmp_path):
    d3 = str(tmp_path / "ad-c")
    save_adapter(d3, _lora_params(3), alpha=ALPHA)
    for name, d in list(setup.dirs.items()) + [("ad-c", d3)]:
        register_adapter(name, d)
    pool = AdapterPool(setup.base, num_slots=2, rank=R, targets=TARGETS)
    m0 = (adapters_mod.loads_total.value, adapters_mod.evictions_total.value,
          adapters_mod.pool_hits_total.value,
          adapters_mod.pool_misses_total.value)

    row_a, loaded = pool.acquire("ad-a")
    assert (row_a, loaded) == (1, True)
    snap_a = _row(pool, row_a)
    assert pool.acquire("ad-a") == (1, False)  # hit, refcount 2
    pool.release(row_a), pool.release(row_a)
    row_b, loaded = pool.acquire("ad-b")
    assert (row_b, loaded) == (2, True)
    pool.release(row_b)
    # Pool full of unpinned rows: ad-c evicts the LRU (ad-a).
    row_c, loaded = pool.acquire("ad-c")
    assert loaded and row_c == 1
    assert not pool.resident("ad-a") and pool.resident("ad-c")
    pool.release(row_c)
    # Re-load after eviction: the scattered rows are byte-identical to
    # the first load (the digest-verified store round-trips exactly).
    row_a2, loaded = pool.acquire("ad-a")
    assert loaded
    jax.tree_util.tree_map(np.testing.assert_array_equal,
                           snap_a, _row(pool, row_a2))
    assert pool.loaded_names() == ["ad-a", "ad-c"]

    d_loads, d_evict, d_hits, d_miss = (
        adapters_mod.loads_total.value - m0[0],
        adapters_mod.evictions_total.value - m0[1],
        adapters_mod.pool_hits_total.value - m0[2],
        adapters_mod.pool_misses_total.value - m0[3])
    assert (d_loads, d_evict, d_hits, d_miss) == (4, 2, 1, 4)
    assert adapters_mod.pool_slots_gauge.value == 2
    assert adapters_mod.pool_bytes_gauge.value == pool.nbytes


def test_pool_full_of_pinned_rows_defers(setup):
    register_adapter("ad-a", setup.dirs["ad-a"])
    register_adapter("ad-b", setup.dirs["ad-b"])
    pool = AdapterPool(setup.base, num_slots=1, rank=R, targets=TARGETS)
    row, _ = pool.acquire("ad-a")
    # The only row is pinned: the caller must defer, not evict or raise.
    assert pool.acquire("ad-b") == (-1, False)
    pool.release(row)
    row_b, loaded = pool.acquire("ad-b")
    assert loaded and row_b == row
    assert not pool.resident("ad-a")


def test_pool_rejects_incompatible_adapters(setup):
    register_adapter("ad-a", setup.dirs["ad-a"])
    # Rank above the pool ceiling: refused AND unregistered (404 next).
    pool = AdapterPool(setup.base, num_slots=2, rank=R - 2, targets=TARGETS)
    with pytest.raises(AdapterError, match="exceeds the pool rank"):
        pool.acquire("ad-a")
    assert "ad-a" not in get_catalog()
    # Adapter trained on modules the pool does not cover.
    register_adapter("ad-b", setup.dirs["ad-b"])
    narrow = AdapterPool(setup.base, num_slots=2, rank=R,
                         targets=("q_proj",))
    with pytest.raises(AdapterError, match="outside this pool"):
        narrow.acquire("ad-b")


def test_gateway_adapter_map_parsing():
    from dlti_tpu.serving.gateway import parse_adapter_map

    assert parse_adapter_map("acme:ad-a, beta:ad-b") == {
        "acme": "ad-a", "beta": "ad-b"}
    assert parse_adapter_map("") == {}


# ----------------------------------------------------------------------
# Tier-1 equivalence: shared-base batched adapters == merged engines
# ----------------------------------------------------------------------

def _check_equivalence(setup, shared_base, merged, quant, logprob_atol):
    """One shared-base engine serving a heterogeneous batch vs a
    merged-weights engine per adapter (+ an adapter-free engine for base
    rows): token streams must match exactly, greedy and seeded."""
    for name, d in setup.dirs.items():
        register_adapter(name, d)
    ec_shared = _ec(adapter_slots=2, adapter_rank=R, quantization=quant)
    shared = InferenceEngine(CFG, shared_base, ec_shared)
    refs = {
        "": InferenceEngine(CFG, shared_base, _ec(quantization=quant)),
        "ad-a": InferenceEngine(CFG, merged["ad-a"],
                                _ec(quantization=quant)),
        "ad-b": InferenceEngine(CFG, merged["ad-b"],
                                _ec(quantization=quant)),
    }
    assign = [(PROMPTS[0], "ad-a"), (PROMPTS[1], "ad-b"),
              (PROMPTS[2], ""), (PROMPTS[3], "ad-a")]
    for sp in (GREEDY, SEEDED):
        reqs = [shared.submit(p, sp, adapter=name) for p, name in assign]
        shared.step()
        # The heterogeneous batch is real: both adapters resident, several
        # rows in flight in the SAME engine at once.
        assert shared.adapter_pool.loaded_names() == ["ad-a", "ad-b"]
        assert shared.num_active >= 2
        got = _drain(shared, reqs)
        for (prompt, name), g in zip(assign, got):
            want = refs[name].generate([prompt], sp)[0]
            assert g.output_token_ids == want.output_token_ids, \
                (name, "seeded" if sp.seed else "greedy")
            np.testing.assert_allclose(g.output_logprobs,
                                       want.output_logprobs,
                                       atol=logprob_atol)
    # The adapters actually steer generation (zero-delta would pass the
    # equality vacuously).
    base_tok = refs[""].generate([PROMPTS[0]], GREEDY)[0].output_token_ids
    assert refs["ad-a"].generate(
        [PROMPTS[0]], GREEDY)[0].output_token_ids != base_tok
    # Unknown adapter fails THAT request (the HTTP layer 404s before it
    # ever reaches an engine; this is the engine-side backstop) — and the
    # engine keeps serving base requests byte-identically afterwards.
    bad = _drain(shared, [shared.submit(PROMPTS[0], GREEDY,
                                        adapter="ghost")])[0]
    assert bad.finish_reason == "error" and not bad.output_token_ids
    ok = _drain(shared, [shared.submit(PROMPTS[0], GREEDY)])[0]
    assert ok.output_token_ids == base_tok


def test_batched_adapters_match_merged_engines_bf16(setup):
    """bf16-resident base: the shared engine holds genuine bf16 weight
    arrays (production storage; f32 accumulation). The merged oracle
    folds the f32 delta over the SAME bf16-rounded base values without
    re-rounding the sum to bf16 — re-rounding would corrupt the oracle
    with merge-time quantization noise that has nothing to do with the
    batched-gather path under test."""
    shared_base = _bf16(setup.base)
    merged = {name: merge_lora_params(_bf16_round_base(setup.trees[name]),
                                      alpha=int(ALPHA))
              for name in setup.trees}
    _check_equivalence(setup, shared_base, merged, "none",
                       logprob_atol=1e-4)


def test_batched_adapters_match_merged_engines_int8(setup):
    """int8 base: both engines quantize the same (identical-values) base,
    so they share one int8 grid; the adapter delta rides outside it."""
    _check_equivalence(setup, setup.base, setup.merged, "int8",
                       logprob_atol=1e-4)


# ----------------------------------------------------------------------
# Slow integration: hot-register, failover, train→serve
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_hot_register_while_engine_is_mid_decode(setup):
    """A name registered AFTER engine construction, while a request is
    mid-decode, serves from the very next admission — no restart, no
    recompile-induced fault, and the in-flight stream is untouched."""
    eng = InferenceEngine(CFG, setup.base, _ec(adapter_slots=2,
                                               adapter_rank=R))
    long_req = eng.submit(PROMPTS[0], SamplingParams(temperature=0.0,
                                                     max_tokens=32))
    for _ in range(4):
        eng.step()
    assert long_req.finish_reason is None  # genuinely mid-decode
    register_adapter("ad-hot", setup.dirs["ad-a"])
    hot = eng.submit(PROMPTS[1], GREEDY, adapter="ad-hot")
    res = _drain(eng, [long_req, hot])
    assert [r.finish_reason for r in res] == ["length", "length"]
    assert eng.adapter_pool.resident("ad-hot")
    want = InferenceEngine(CFG, setup.merged["ad-a"], _ec()).generate(
        [PROMPTS[1]], GREEDY)[0]
    assert res[1].output_token_ids == want.output_token_ids


@pytest.mark.slow
def test_replica_failover_resubmit_preserves_adapter(setup, devices):
    """A replica fault mid-flight: its requests resubmit on the survivor
    and finish under the SAME adapter — zero client-visible errors,
    greedy streams identical to an unfaulted engine."""
    for name, d in setup.dirs.items():
        register_adapter(name, d)
    ec = _ec(adapter_slots=2, adapter_rank=R)
    rep = ReplicatedEngine(CFG, setup.base, ec, replicas=2, tensor=1,
                           devices=devices[:2], max_retries=2,
                           fault_inject_step="0:3")
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    assign = [(PROMPTS[i % 4], ("ad-a", "ad-b")[i % 2]) for i in range(6)]
    reqs = [rep.submit(p, sp, adapter=name) for p, name in assign]
    while rep.has_work:
        rep.step()
    assert rep.failover["replica_faults"] == 1
    results = [rep.engines[r.replica]._result(r) for r in reqs]
    for (_, name), req, res in zip(assign, reqs, results):
        assert req.adapter == name  # the adapter rode the resubmit
        assert res.finish_reason == "length", res
    single = InferenceEngine(CFG, setup.base, ec)
    for (prompt, name), res in zip(assign, results):
        want = _drain(single, [single.submit(prompt, sp, adapter=name)])[0]
        assert res.output_token_ids == want.output_token_ids, name


@pytest.mark.slow
def test_train_save_register_generate_e2e(tmp_path):
    """The loop the tentpole closes: a LoRA checkpoint the Trainer just
    wrote becomes servable on a running shared-base engine via
    hot-register — and matches the merged-weights export exactly."""
    from dlti_tpu.config import (
        CheckpointConfig, Config, DataConfig, OptimizerConfig,
        ParallelConfig, TrainConfig, ZeROStage,
    )
    from dlti_tpu.data import (
        ByteTokenizer, format_conversation_for_llama2, make_batches,
    )
    from dlti_tpu.training.trainer import Trainer

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=R, alpha=int(ALPHA), dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(zero_stage=ZeROStage.ZERO2, data=8),
        data=DataConfig(max_seq_len=64, tokenizer="byte"),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_steps=4, async_save=False),
        train=TrainConfig(max_steps=8, micro_batch_size=8,
                          grad_accum_steps=2,
                          metrics_csv=str(tmp_path / "metrics.csv")),
    )
    texts = [format_conversation_for_llama2(
        {"question": f"What is {i}?", "answer": f"It is {i}."})["text"]
        for i in range(200)]
    ds = make_batches(texts, ByteTokenizer(), seq_len=64,
                      micro_batch_size=8, grad_accum_steps=2,
                      shard_by_host=False)
    state, _ = Trainer(cfg).train(dataset=ds)
    params = jax.tree_util.tree_map(np.asarray, state.params)

    # Engine FIRST (serving the base), register AFTER: no restart.
    base = merge_lora_params(params, scaling=0.0)
    eng = InferenceEngine(CFG, base, _ec(adapter_slots=2, adapter_rank=R))
    assert _drain(eng, [eng.submit(PROMPTS[0], GREEDY)])[0].output_token_ids

    save_adapter(str(tmp_path / "trained"), params, alpha=ALPHA)
    register_adapter("trained", str(tmp_path / "trained"))
    got = _drain(eng, [eng.submit(PROMPTS[0], GREEDY,
                                  adapter="trained")])[0]
    want = InferenceEngine(CFG, merge_lora_params(params, alpha=int(ALPHA)),
                           _ec()).generate([PROMPTS[0]], GREEDY)[0]
    assert got.output_token_ids == want.output_token_ids
    assert got.finish_reason == "length"
