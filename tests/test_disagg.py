"""Prefill/decode disaggregation (dlti_tpu.serving.disagg) — tier 1.

Layers, mirroring the subsystem's own structure:

* **Scheduler/executor split**: the engine's device half lives on
  :class:`EngineExecutor`; the engine proper is host scheduling plus
  delegation — the unit contract the disagg controller builds on.
* **Paged-KV handoff**: block payloads fetched from a prefill engine and
  scattered into a decode engine are byte-equal on arrival, for bf16 AND
  int8 pools (scales travel with the payload).
* **Byte-identity**: completions with disaggregation on vs off are
  token-for-token identical — greedy and seeded-sampled, bf16 and int8
  KV — because the handoff carries the sampled first token and the
  origin slot's rng key bytes (fold_in stream continuity).
* **Failover drills**: killing a prefill-pool or decode-pool replica
  mid-run completes every request with zero client-visible errors.
* **Backpressure & shed**: staging queues respect handoff_queue_depth;
  a staged snapshot past handoff_deadline_s degrades to a decode-side
  re-prefill (counted, never an error).
* **Ledger pin**: the note_requeue fold — a second requeue before
  re-admission (preempt mid-chunked-prefill, then replica death) books
  BOTH windows instead of silently dropping the first.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.config import MODEL_PRESETS
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import (
    DisaggController, EngineConfig, InferenceEngine, SamplingParams,
)
from dlti_tpu.serving.engine import EngineExecutor, Request
from dlti_tpu.telemetry.ledger import note_readmitted, note_requeue

CFG = MODEL_PRESETS["llama_tiny"]


@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _ec(**over):
    base = dict(max_seqs=4, block_size=8, num_blocks=64, max_model_len=128,
                cache_dtype="float32", eos_token_id=-1)
    base.update(over)
    return EngineConfig(**base)


PROMPTS = [[1, 2, 3, 4, 5], [6, 7, 8], [9, 10, 11, 12], [13, 14]]


# ----------------------------------------------------------------------
# Scheduler/executor split
# ----------------------------------------------------------------------

def test_executor_owns_device_half_and_engine_delegates(tiny_params):
    eng = InferenceEngine(CFG, tiny_params, _ec())
    assert isinstance(eng.executor, EngineExecutor)
    # Delegation is identity, not a copy: the engine's params/cache ARE
    # the executor's (replica NaN-poisoning and the memledger lambdas
    # depend on writing through).
    assert eng.params is eng.executor.params
    assert eng.cache is eng.executor.cache
    marker = jax.tree_util.tree_map(lambda x: x, eng.executor.params)
    eng.params = marker
    assert eng.executor.params is marker
    # The block transport the handoff rides lives on the executor class;
    # the engine keeps only thin delegating wrappers.
    for name in ("fetch_block_kv", "restore_block"):
        assert name in EngineExecutor.__dict__
        assert name not in InferenceEngine.__dict__


def test_prefill_only_engine_never_decodes(tiny_params):
    eng = InferenceEngine(CFG, tiny_params, _ec())
    eng.prefill_only = True
    req = eng.submit([1, 2, 3, 4], SamplingParams(max_tokens=8))
    for _ in range(20):
        eng.step()
    # Prefill ran (first token sampled), decode never did: the slot sits
    # harvestable with exactly one output token.
    assert req.output_token_ids and len(req.output_token_ids) == 1
    slot = next(s for s in eng.slots if s.request is req)
    assert not slot.prefilling and slot.last_token is not None
    assert eng.has_work  # still occupied: backpressure, not completion


# ----------------------------------------------------------------------
# Paged-KV handoff byte-equality
# ----------------------------------------------------------------------

def _prefill_and_export(src, prompt, params):
    req = src.submit(prompt, params)
    for _ in range(50):
        src.step()
        slot = next((s for s in src.slots if s.request is req), None)
        if slot is not None and not slot.prefilling \
                and slot.last_token is not None:
            break
    else:
        pytest.fail("prefill never completed")
    snap = src.export_handoff(slot)
    assert snap is not None
    return req, snap


@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
def test_handoff_blocks_byte_equal_after_restore(tiny_params, kv_dtype):
    ec = _ec(cache_dtype=kv_dtype)
    src = InferenceEngine(CFG, tiny_params, ec)
    dst = InferenceEngine(CFG, tiny_params, ec)
    src.prefill_only = True
    prompt = list(range(3, 3 + 21))  # 21 tokens -> 3 blocks at block 8
    req, snap = _prefill_and_export(
        src, prompt, SamplingParams(max_tokens=4))
    assert len(snap["payloads"]) == 3
    if kv_dtype == "int8":
        # Scales must travel with the int8 payload.
        layer0 = next(iter(snap["payloads"][0].values()))
        assert any("scale" in k for k in layer0)
    assert dst.adopt_handoff(snap)
    slot = next(s for s in dst.slots if s.request is req)
    for got, sent in zip((dst._fetch_block_kv(b) for b in slot.blocks),
                         snap["payloads"]):
        assert got is not None
        assert set(got) == set(sent)
        for lk in got:
            assert set(got[lk]) == set(sent[lk])
            for ak in got[lk]:
                np.testing.assert_array_equal(
                    np.asarray(got[lk][ak]), np.asarray(sent[lk][ak]))


def test_handoff_preserves_rng_key_and_counts(tiny_params):
    src = InferenceEngine(CFG, tiny_params, _ec())
    src.prefill_only = True
    req, snap = _prefill_and_export(
        src, [5, 6, 7], SamplingParams(max_tokens=4, temperature=0.8))
    assert snap["gen_count"] == 1
    assert snap["last_token"] == req.output_token_ids[0]
    dst = InferenceEngine(CFG, tiny_params, _ec())
    assert dst.adopt_handoff(snap)
    slot = next(s for s in dst.slots if s.request is req)
    np.testing.assert_array_equal(dst._slot_keys[slot.slot_id],
                                  snap["slot_key"])
    assert int(dst._gen_counts[slot.slot_id]) == 1


# ----------------------------------------------------------------------
# Byte-identity: disaggregation on vs off
# ----------------------------------------------------------------------

@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
@pytest.mark.parametrize("sp", [
    SamplingParams(max_tokens=8, temperature=0.0),              # greedy
    SamplingParams(max_tokens=8, temperature=0.9, seed=7),      # sampled
], ids=["greedy", "seeded-sampled"])
def test_outputs_identical_disagg_on_vs_off(tiny_params, devices,
                                            kv_dtype, sp):
    ec = _ec(cache_dtype=kv_dtype)
    base = InferenceEngine(CFG, tiny_params, ec)
    expect = [r.output_token_ids for r in base.generate(PROMPTS, sp)]
    ctl = DisaggController(CFG, tiny_params, ec, prefill_replicas=1,
                           decode_replicas=2, devices=devices[:3])
    got = [r.output_token_ids for r in ctl.generate(PROMPTS, sp)]
    assert got == expect
    assert ctl.handoff["completed"] >= len(PROMPTS)


# ----------------------------------------------------------------------
# Kill drills: either pool loses a replica, zero client errors
# ----------------------------------------------------------------------

def _assert_all_completed(results, n):
    assert len(results) == n
    bad = [r for r in results if r.finish_reason not in ("stop", "length")]
    assert not bad, [f"{r.request_id}:{r.finish_reason}" for r in bad]


def test_prefill_replica_kill_drill(tiny_params, devices):
    # Step 1: a prefill engine drains its whole admission in one step
    # (short prompts), so the injected fault must land on the replica's
    # first worked step to hit it mid-flight.
    ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=2,
                           decode_replicas=1, devices=devices[:3],
                           fault_inject_step="prefill:0:1")
    res = ctl.generate(PROMPTS * 2, SamplingParams(max_tokens=8))
    _assert_all_completed(res, len(PROMPTS) * 2)
    assert ctl.prefill.num_live == 1
    assert ctl.failover["replica_faults"] == 1


def test_decode_replica_kill_drill(tiny_params, devices):
    ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=1,
                           decode_replicas=2, devices=devices[:3],
                           fault_inject_step="decode:0:3")
    res = ctl.generate(PROMPTS * 2, SamplingParams(max_tokens=8))
    _assert_all_completed(res, len(PROMPTS) * 2)
    assert ctl.decode.num_live == 1
    assert ctl.failover["replica_faults"] == 1


def test_whole_prefill_pool_dead_degrades_to_colocated(tiny_params,
                                                       devices):
    ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=1,
                           decode_replicas=1, devices=devices[:2],
                           fault_inject_step="prefill:0:1")
    res = ctl.generate(PROMPTS, SamplingParams(max_tokens=8))
    _assert_all_completed(res, len(PROMPTS))
    assert ctl.prefill.num_live == 0  # decode pool carried the rest


# ----------------------------------------------------------------------
# Backpressure & deadline shed
# ----------------------------------------------------------------------

def test_staging_respects_queue_depth(tiny_params, devices):
    ctl = DisaggController(CFG, tiny_params,
                           _ec(max_seqs=2, num_blocks=32),
                           prefill_replicas=1, decode_replicas=1,
                           devices=devices[:2], handoff_queue_depth=1)
    reqs = [ctl.submit(p, SamplingParams(max_tokens=16))
            for p in PROMPTS + PROMPTS]
    cap = ctl.handoff_queue_depth * len(ctl.decode.engines)
    for _ in range(600):
        if not ctl.has_work:
            break
        ctl.step()
        assert sum(len(q) for q in ctl._staging) <= cap
    assert not ctl.has_work
    assert all(r.finish_reason in ("stop", "length") for r in reqs)


def test_handoff_deadline_sheds_to_reprefill(tiny_params, devices):
    # Decode pool with 2 slots, 8 competing requests: staged snapshots
    # wait, the tiny deadline trips, and the shed path re-prefills on the
    # decode replica — latency, never an error.
    ctl = DisaggController(CFG, tiny_params,
                           _ec(max_seqs=2, num_blocks=32),
                           prefill_replicas=1, decode_replicas=1,
                           devices=devices[:2], handoff_deadline_s=1e-4)
    res = ctl.generate(PROMPTS + PROMPTS, SamplingParams(max_tokens=16))
    _assert_all_completed(res, len(PROMPTS) * 2)
    assert ctl.handoff["sheds"] > 0


def test_concurrent_mode_completes_everything(tiny_params, devices):
    # The production serve path: prefill pool on its own thread. Not a
    # byte-identity test (scheduling is timing-dependent) — a liveness
    # and zero-error drill.
    ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=1,
                           decode_replicas=1, devices=devices[:2])
    ctl.start()
    try:
        reqs = [ctl.submit(p, SamplingParams(max_tokens=8))
                for p in PROMPTS * 3]
        deadline = time.monotonic() + 60
        while ctl.has_work and time.monotonic() < deadline:
            ctl.step()
    finally:
        ctl.stop()
    assert all(r.finish_reason in ("stop", "length") for r in reqs)


# ----------------------------------------------------------------------
# Phase accounting
# ----------------------------------------------------------------------

def test_handoff_books_as_kv_handoff_phase(tiny_params, devices):
    from dlti_tpu.telemetry.ledger import request_breakdown

    ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=1,
                           decode_replicas=1, devices=devices[:2])
    req = ctl.submit([1, 2, 3, 4, 5], SamplingParams(max_tokens=8))
    while ctl.has_work:
        ctl.step()
    assert req.finish_reason in ("stop", "length")
    assert "kv_handoff" in req.stall_s
    phases = request_breakdown(req)
    assert phases.get("kv_handoff", 0.0) >= 0.0
    assert ctl.handoff["completed"] == 1


def test_handoff_span_carries_trace_context(tiny_params, devices):
    """Distributed-trace survival across the disagg staging path: the
    request's trace_id (minted at submit) rides into the
    engine/kv_handoff span, and the per-request timeline shows the
    staging leg between prefill and decode."""
    from dlti_tpu.telemetry import get_tracer
    from dlti_tpu.telemetry.distributed_trace import request_timeline

    tracer = get_tracer()
    prev = tracer.enabled
    tracer.enabled = True
    try:
        ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=1,
                               decode_replicas=1, devices=devices[:2])
        req = ctl.submit([1, 2, 3, 4, 5], SamplingParams(max_tokens=8))
        assert len(req.trace_id) == 16
        while ctl.has_work:
            ctl.step()
        assert req.finish_reason in ("stop", "length")
        spans = [ev for ev in tracer.events()
                 if ev.get("name") == "engine/kv_handoff"
                 and (ev.get("args") or {}).get("id") == req.request_id]
        assert spans, "staging must emit the kv_handoff span"
        assert all(s["args"].get("trace") == req.trace_id for s in spans)
        tl = request_timeline(tracer.events(), req.request_id)
        assert tl["trace_id"] == req.trace_id
        assert {"engine/kv_handoff", "request/prefill",
                "request/decode"} <= set(tl["legs"]), sorted(tl["legs"])
        # The staging window overlaps the lifecycle legs: reported but
        # never counted toward the sequential coverage.
        assert "engine/kv_handoff" not in tl["sequential_legs"]
    finally:
        tracer.enabled = prev


def test_note_requeue_folds_open_mark_instead_of_dropping_it():
    """The mid-chunked-prefill double-requeue bug: a slot preempted
    mid-prompt has an open "preempt" mark; its replica then dies and
    note_requeue("failover") fires BEFORE any re-admission closed the
    window. The old overwrite dropped the preempt wait (it silently
    rebooked into prefill); the fold must keep both windows and
    accumulate stall_prefill_s across re-admissions."""
    req = Request(request_id="r", prompt_token_ids=[1, 2, 3],
                  params=SamplingParams())
    note_requeue(req, "preempt")
    time.sleep(0.012)
    note_requeue(req, "failover")  # second requeue, mark still open
    time.sleep(0.012)
    note_readmitted(req)
    assert req.stall_s.get("preempt", 0.0) >= 0.01
    assert req.stall_s.get("failover", 0.0) >= 0.01
    # No first token yet -> both windows charge the prefill-side stall.
    assert req.stall_prefill_s >= req.stall_s["preempt"] + \
        req.stall_s["failover"] - 1e-6


# ----------------------------------------------------------------------
# Metrics & registry exposition
# ----------------------------------------------------------------------

def test_registry_exposes_pool_and_handoff_metrics(tiny_params, devices):
    import types

    from dlti_tpu.serving.server import build_registry

    ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=1,
                           decode_replicas=1, devices=devices[:2])
    registry = build_registry(types.SimpleNamespace(engine=ctl))
    names = registry.metric_names()
    from dlti_tpu.serving.disagg import (
        KV_HANDOFF_METRIC_NAMES, POOL_METRIC_NAMES,
    )

    ctl.generate([[1, 2, 3]], SamplingParams(max_tokens=4))
    exposition = registry.render_prometheus()
    for name in POOL_METRIC_NAMES + KV_HANDOFF_METRIC_NAMES:
        assert name in exposition, name
    assert "dlti_kv_handoff_seconds" in names


def test_stats_surface_aggregates_pools(tiny_params, devices):
    ctl = DisaggController(CFG, tiny_params, _ec(), prefill_replicas=1,
                           decode_replicas=1, devices=devices[:2])
    ctl.generate(PROMPTS, SamplingParams(max_tokens=4))
    s = ctl.stats
    # Admission counts once, on the prefill pool; the decode-side
    # adoption (like resubmit) does not double count.
    assert s["requests"] == len(PROMPTS)
    assert set(s["pools"]) == {"prefill", "decode"}
    assert s["kv_handoff"]["completed"] == len(PROMPTS)
    # Handoff staging is a pinned memory-ledger owner on decode engines.
    for eng in ctl.decode.engines:
        assert "kv_handoff_staging" in eng.memledger.owners()
