"""Continuous delivery (dlti_tpu.serving.deploy).

Layers, mirroring the subsystem's own structure:

* **State-machine units** (fake clock, fake engines, real checkpoint
  store underneath): watch -> export -> canary -> promote; canary gate
  failure -> rollback + quarantine + refused-forever; flapping
  candidates respect exponential promotion backoff; operator
  disable/enable cancels without judging.
* **Shadow-tap accounting**: mirrored canary traffic is flagged
  ``shadow`` end to end, never books into the client-facing request
  histograms, and is sampled/bounded by the tap itself.
* **Mid-roll re-verification** (real tiny fleet): an export bit-flipped
  AFTER the first replica swapped aborts the rest of the roll
  (``request_reload(verify=...)``), instead of shipping different bytes
  to different replicas.
* **Watchdog rule**: ``canary_regression`` fires on rollback-counter
  growth in the ring, once per episode, silent at limit 0.
* **Server surface**: GET/POST ``/v1/deploy``; ``deploy.json`` rides in
  every flight dump.

The live train->serve poisoned-checkpoint drills live in
``tests/test_deploy_drill.py`` under ``@pytest.mark.slow``.
"""

import http.client
import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from dlti_tpu.checkpoint.store import (
    load_pytree, manifest_digest, save_pytree, verify_pytree_dir,
)
from dlti_tpu.config import DeployConfig, WatchdogConfig
from dlti_tpu.serving import deploy as deploy_mod
from dlti_tpu.serving.deploy import DeploymentController
from dlti_tpu.telemetry import (
    AnomalyWatchdog, SpanTracer, TimeSeriesSampler,
)


# ----------------------------------------------------------------------
# Fakes: a request/engine pair shaped like the real ones, and a fleet
# facade with the reload surface the controller drives.
# ----------------------------------------------------------------------

class _Req:
    def __init__(self, rid="r", out=(1, 2, 3), logprob=-1.0, done=False):
        self.request_id = rid
        self.prompt_token_ids = [1, 2, 3, 4]
        self.arrival_time = 0.0
        self.first_token_time = 0.01 if done else None
        self.finish_time = 0.02 if done else None
        self.finish_reason = "stop" if done else None
        self.output_token_ids = list(out) if done else []
        self.output_logprobs = [logprob] * len(out) if done else []
        self.admitted_time = None
        self.num_preemptions = 0
        self.shadow = False

    @property
    def done(self):
        return self.finish_reason is not None


class FakeEngine:
    """Canary-engine stand-in: submit() queues, step() finishes."""

    def __init__(self, logprob=-1.0, out_len=3, error=False):
        self.logprob = logprob
        self.out_len = out_len
        self.error = error
        self.pending = []
        self.all_requests = []
        self.closed = False

    def submit(self, prompt, params, request_id=None):
        req = _Req(request_id or f"r{len(self.all_requests)}")
        self.pending.append(req)
        self.all_requests.append(req)
        return req

    @property
    def has_work(self):
        return bool(self.pending)

    def step(self):
        for req in self.pending:
            req.output_token_ids = [1] * self.out_len
            req.output_logprobs = [float(self.logprob)] * self.out_len
            req.first_token_time = req.arrival_time + 0.001
            req.finish_time = req.arrival_time + 0.002
            req.finish_reason = "error" if self.error else "stop"
        self.pending = []
        return []

    def close(self):
        self.closed = True


class FakeFleet:
    """The serving facade the controller promotes through."""

    def __init__(self):
        self.shadow_tap = None
        self._reload = None
        self.last_reload_ok = None
        self.reload_calls = []

    def request_reload(self, provider, *, verify=None):
        if self._reload is not None:
            return False
        self._reload = {"provider": provider, "verify": verify}
        self.reload_calls.append(self._reload)
        return True

    def finish_roll(self, ok=True):
        """Simulate the stepper completing (or aborting) the roll."""
        self._reload = None
        self.last_reload_ok = ok


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _counters():
    return {
        "candidates": deploy_mod.candidates_total.value,
        "canaries": deploy_mod.canaries_total.value,
        "promotions": deploy_mod.promotions_total.value,
        "rollbacks": deploy_mod.rollbacks_total.value,
        "rejected": deploy_mod.rejected_total.value,
    }


def _delta(before):
    after = _counters()
    return {k: after[k] - before[k] for k in after}


def _write_step(watch_dir, step, scale=1.0):
    """A committed, verified 'training checkpoint' the watch loop sees
    (save_pytree speaks the same manifest+COMMIT protocol)."""
    save_pytree(os.path.join(watch_dir, str(step)),
                {"w": np.full((2, 2), float(step) * scale, np.float32)})


def _controller(tmp_path, *, factories=None, clock=None, **cfg_over):
    """A controller over a real watch/export tree with fake engines.

    ``factories(export_dir) -> FakeEngine`` decides canary behavior per
    directory; the default is a healthy engine matching the incumbent.
    """
    watch = str(tmp_path / "watch")
    os.makedirs(watch, exist_ok=True)
    incumbent = save_pytree(str(tmp_path / "incumbent"),
                            {"w": np.zeros((2, 2), np.float32)})
    kw = dict(enabled=True, watch_dir=watch,
              export_dir=str(tmp_path / "exports"),
              poll_interval_s=1.0, canary_shadow_frac=1.0,
              canary_min_requests=2, canary_max_wait_s=60.0,
              promote_max_logprob_drift=0.25,
              probe_prompts=2, probe_prompt_tokens=4, probe_max_tokens=3,
              promote_backoff_s=30.0, promote_backoff_factor=2.0)
    kw.update(cfg_over)
    engines = {}

    def factory(export_dir):
        if export_dir not in engines:
            engines[export_dir] = (factories(export_dir) if factories
                                   else FakeEngine())
        return engines[export_dir]

    fleet = FakeFleet()
    clk = clock or _Clock()

    def exporter(watch_dir, step, out_dir):
        src = load_pytree(os.path.join(watch_dir, str(step)), verify=True)
        save_pytree(out_dir, src)
        return manifest_digest(out_dir)

    ctrl = DeploymentController(
        fleet, DeployConfig(**kw), exporter=exporter,
        canary_factory=factory, incumbent_dir=incumbent, clock=clk)
    return ctrl, fleet, clk, watch, engines


def _mirror(fleet, n, out=(1, 2, 3)):
    """Feed n completed live requests through the installed shadow tap
    (what ReplicatedEngine.submit does per client request)."""
    for i in range(n):
        live = _Req(f"live-{i}", out=out, done=True)
        fleet.shadow_tap([1, 2, 3, 4], None, live)


# ----------------------------------------------------------------------
# watch -> export -> canary -> promote
# ----------------------------------------------------------------------

def test_watch_export_canary_promote(tmp_path):
    before = _counters()
    ctrl, fleet, clk, watch, engines = _controller(tmp_path)
    _write_step(watch, 7)

    ctrl.tick()
    assert ctrl.state == "canary"
    d = _delta(before)
    assert d["candidates"] == 1 and d["canaries"] == 1
    # The candidate export is a real verified artifact.
    export_dir = ctrl._candidate["dir"]
    assert verify_pytree_dir(export_dir)[0]
    assert ctrl._candidate["digest"] == manifest_digest(export_dir)

    # Shadow traffic arrives; gates judge once min pairs complete.
    _mirror(fleet, 3)
    ctrl.tick()
    assert ctrl.state == "promoting"
    assert fleet._reload is not None
    # The reload the controller queued carries a working verify closure
    # and a provider that loads the candidate bytes.
    st = fleet.reload_calls[0]
    assert st["verify"]()
    loaded = st["provider"]()
    np.testing.assert_array_equal(loaded["w"],
                                  np.full((2, 2), 7.0, np.float32))

    # Roll completes -> incumbent flips, baseline re-pins, back to idle.
    fleet.finish_roll(ok=True)
    ctrl.tick()
    assert ctrl.state == "idle"
    assert ctrl.incumbent_step == 7
    assert ctrl.incumbent_digest == manifest_digest(export_dir)
    d = _delta(before)
    assert d["promotions"] == 1 and d["rollbacks"] == 0
    assert deploy_mod.incumbent_step_gauge.value == 7
    assert ctrl.status()["last_result"]["verdict"] == "promoted"

    # Steady state: the promoted step is not a candidate again.
    clk.t += 10
    ctrl.tick()
    assert ctrl.state == "idle"
    assert _delta(before)["canaries"] == 1


def test_shadow_requests_are_flagged_and_probes_pinned(tmp_path):
    ctrl, fleet, clk, watch, engines = _controller(tmp_path)
    _write_step(watch, 3)
    ctrl.tick()
    _mirror(fleet, 2)
    ctrl.tick()
    # EVERY request the controller ever put on a canary engine carries
    # the shadow flag — probes and mirrors alike — so telemetry/SLO
    # accounting can exclude them wholesale.
    canary_reqs = [r for eng in engines.values() for r in eng.all_requests]
    assert canary_reqs and all(r.shadow for r in canary_reqs)


def test_shadow_twin_shares_live_trace_context(tmp_path):
    """Distributed-trace survival across the shadow-tap replay: the
    mirror twin on the canary engine carries the LIVE request's
    trace_id, so a federated timeline can show the shadowed leg beside
    the client-facing one."""
    ctrl, fleet, clk, watch, engines = _controller(tmp_path)
    _write_step(watch, 3)
    ctrl.tick()
    live = _Req("live-traced", out=(1, 2, 3), done=True)
    live.trace_id = "a1b2c3d4e5f60718"
    fleet.shadow_tap([1, 2, 3, 4], None, live)
    ctrl.tick()
    twins = [r for eng in engines.values() for r in eng.all_requests
             if getattr(r, "trace_id", "") == live.trace_id]
    assert twins, "shadow twin must inherit the live trace id"
    assert all(r.shadow for r in twins)


# ----------------------------------------------------------------------
# canary gate failure -> rollback, quarantine, refused forever
# ----------------------------------------------------------------------

def test_canary_drift_rejects_quarantines_and_refuses(tmp_path):
    before = _counters()

    def factories(export_dir):
        # The incumbent probes at -1.0; candidates probe at -5.0 — a
        # drift of 4.0 against a 0.25 gate.
        bad = "exports" in export_dir
        return FakeEngine(logprob=-5.0 if bad else -1.0)

    ctrl, fleet, clk, watch, engines = _controller(
        tmp_path, factories=factories)
    _write_step(watch, 9)
    ctrl.tick()
    assert ctrl.state == "canary"
    export_dir = ctrl._candidate["dir"]
    _mirror(fleet, 3)
    ctrl.tick()

    # Verdict: rolled back without the fleet ever being touched.
    assert ctrl.state == "idle"
    assert fleet.reload_calls == []
    assert ctrl.incumbent_step == -1
    d = _delta(before)
    assert d["rollbacks"] == 1 and d["rejected"] == 1
    assert d["promotions"] == 0
    res = ctrl.status()["last_result"]
    assert res["verdict"] == "rolled-back"
    assert any(r.startswith("drift:") for r in res["reasons"])

    # The rejected export moved into quarantine for forensics.
    assert not os.path.exists(export_dir)
    qdir = os.path.join(os.path.dirname(export_dir), "_quarantine")
    assert os.path.isdir(qdir) and os.listdir(qdir)

    # Refused forever: later ticks skip step 9 entirely...
    clk.t += 100
    ctrl.tick()
    assert ctrl.state == "idle"
    assert _delta(before)["canaries"] == 1
    # ...and the refusal survives a controller restart (persisted).
    ctrl2 = DeploymentController(
        FakeFleet(), ctrl.cfg, exporter=ctrl.exporter,
        canary_factory=ctrl.canary_factory, clock=clk)
    assert 9 in ctrl2._refused


def test_numeric_gate_rejects_errored_shadow(tmp_path):
    before = _counters()

    def factories(export_dir):
        return FakeEngine(error="exports" in export_dir)

    ctrl, fleet, clk, watch, engines = _controller(
        tmp_path, factories=factories)
    _write_step(watch, 4)
    ctrl.tick()
    # Probes error out -> the numeric gate rejects before any shadow
    # traffic is even needed.
    ctrl.tick()
    assert ctrl.state == "idle"
    d = _delta(before)
    assert d["rollbacks"] == 1 and d["promotions"] == 0
    reasons = ctrl.status()["last_result"]["reasons"]
    assert any(r.startswith("numeric:") for r in reasons)


def test_midroll_abort_counts_as_rollback_and_refuses(tmp_path):
    """A promotion that aborts mid-roll (per-swap re-verify, in-roll
    canary failure) still books a rollback and refuses the step."""
    before = _counters()
    ctrl, fleet, clk, watch, engines = _controller(tmp_path)
    _write_step(watch, 5)
    ctrl.tick()
    _mirror(fleet, 3)
    ctrl.tick()
    assert ctrl.state == "promoting"
    fleet.finish_roll(ok=False)
    ctrl.tick()
    assert ctrl.state == "idle"
    d = _delta(before)
    assert d["rollbacks"] == 1
    assert 5 in ctrl._refused
    assert ctrl.incumbent_step == -1


# ----------------------------------------------------------------------
# flapping candidates: exponential promotion backoff
# ----------------------------------------------------------------------

def test_flapping_candidates_respect_promotion_backoff(tmp_path):
    def factories(export_dir):
        return FakeEngine(logprob=-9.0 if "exports" in export_dir
                          else -1.0)

    ctrl, fleet, clk, watch, engines = _controller(
        tmp_path, factories=factories)
    before = _counters()
    _write_step(watch, 1)
    ctrl.tick()
    _mirror(fleet, 3)
    ctrl.tick()  # reject #1 -> backoff 30s
    assert _delta(before)["rollbacks"] == 1
    assert ctrl._backoff_until == pytest.approx(clk.t + 30.0)

    # A fresh (equally bad) candidate lands immediately; the controller
    # must NOT canary it until the backoff elapses.
    _write_step(watch, 2)
    clk.t += 10
    ctrl.tick()
    assert ctrl.state == "idle"
    assert _delta(before)["canaries"] == 1

    clk.t += 25  # past the 30s backoff
    ctrl.tick()
    assert ctrl.state == "canary"
    _mirror(fleet, 3)
    ctrl.tick()  # reject #2 -> backoff doubles to 60s
    assert _delta(before)["rollbacks"] == 2
    assert ctrl._consecutive_rollbacks == 2
    assert ctrl._backoff_until == pytest.approx(clk.t + 60.0)


# ----------------------------------------------------------------------
# operator disable/enable
# ----------------------------------------------------------------------

def test_disable_cancels_canary_without_judging(tmp_path):
    before = _counters()
    ctrl, fleet, clk, watch, engines = _controller(tmp_path)
    _write_step(watch, 6)
    ctrl.tick()
    assert ctrl.state == "canary"

    ctrl.set_enabled(False)
    assert ctrl.state == "idle"
    assert ctrl.status()["last_result"]["verdict"] == "cancelled"
    # Cancelled, not judged: no rollback booked, step NOT refused.
    assert _delta(before)["rollbacks"] == 0
    assert 6 not in ctrl._refused

    # Disabled controller ignores the watch dir entirely.
    clk.t += 100
    ctrl.tick()
    assert ctrl.state == "idle"

    # Re-enable: the same step is eligible again.
    ctrl.set_enabled(True)
    clk.t += 10
    ctrl.tick()
    assert ctrl.state == "canary"
    assert ctrl._candidate["step"] == 6


# ----------------------------------------------------------------------
# shadow-tap accounting
# ----------------------------------------------------------------------

def test_tap_samples_fraction_and_only_in_canary(tmp_path):
    ctrl, fleet, clk, watch, engines = _controller(
        tmp_path, canary_shadow_frac=0.25, canary_min_requests=100)
    # Outside a canary phase the tap is a no-op.
    _mirror(fleet, 8)
    assert ctrl.status()["shadow"]["seen"] == 0

    _write_step(watch, 2)
    ctrl.tick()
    assert ctrl.state == "canary"
    _mirror(fleet, 40)
    st = ctrl.status()["shadow"]
    assert st["seen"] == 40
    # Fractional accumulator: exactly frac * seen mirrors, no rounding
    # drift.
    assert st["mirrored"] == 10


def test_shadow_requests_excluded_from_client_histograms():
    from dlti_tpu.serving.engine import Request
    from dlti_tpu.telemetry import RequestTelemetry

    rt = RequestTelemetry(tracer=SpanTracer(enabled=False))

    def _real_req(rid, shadow):
        return Request(request_id=rid, prompt_token_ids=[1, 2, 3],
                       arrival_time=0.0, output_token_ids=[4, 5, 6],
                       output_logprobs=[-1.0] * 3, first_token_time=0.01,
                       finish_time=0.02, finish_reason="stop",
                       shadow=shadow)

    shadow = _real_req("shadow-1", True)
    live = _real_req("live-1", False)
    for req in (shadow, live):
        rt.on_submitted(req)
        rt.on_admitted(req)
        rt.on_first_token(req)
        rt.on_finished(req)
    # Only the live request booked: the shadow twin is invisible to the
    # client-facing SLIs the SLO objectives are computed from.
    assert rt.ttft._count == 1
    assert rt.tpot._count == 1
    assert rt.queue_time._count == 1
    # The live request's admitted_time got stamped; the shadow's didn't.
    assert live.admitted_time is not None
    assert getattr(shadow, "admitted_time", None) is None


def test_tap_exceptions_never_reach_the_client_path(tmp_path):
    """The facades call the tap inside a try/except: a controller bug
    must never fail a live submit. Unit-checked here against the real
    ReplicatedEngine tap call-site contract (callable attribute)."""
    ctrl, fleet, clk, watch, engines = _controller(tmp_path)
    _write_step(watch, 2)
    ctrl.tick()
    # Stop() uninstalls the tap so a dead controller leaves no hook.
    assert fleet.shadow_tap is not None
    ctrl.stop()
    assert fleet.shadow_tap is None


# ----------------------------------------------------------------------
# mid-roll re-verification on a real tiny fleet (satellite: reload
# digest blind spot)
# ----------------------------------------------------------------------

def test_reload_reverifies_before_each_swap_real_fleet(tmp_path):
    import jax

    from dlti_tpu.checkpoint.chaos import bit_flip_file
    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, ReplicatedEngine

    cfg = MODEL_PRESETS["llama_tiny"]
    import jax.numpy as jnp

    model = LlamaForCausalLM(cfg, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    rep = ReplicatedEngine(
        cfg, params,
        EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                     max_model_len=64, cache_dtype="float32",
                     eos_token_id=-1),
        replicas=2, tensor=1, devices=jax.devices()[:2])
    export = save_pytree(str(tmp_path / "w"), jax.device_get(params))
    expect = manifest_digest(export)

    def _verify():
        return (manifest_digest(export) == expect
                and verify_pytree_dir(export)[0])

    assert rep.request_reload(lambda: load_pytree(export, verify=True),
                              verify=_verify)
    # Drive the roll until exactly one replica has swapped.
    for _ in range(2000):
        rep.step()
        st = rep._reload
        if st is None or (st["queue"] is not None and len(st["queue"]) == 1):
            break
    assert rep._reload is not None, "roll finished before corruption"
    assert len(rep._reload["queue"]) == 1

    # Bytes rot between swap 1 and swap 2: the next tick's re-verify
    # must abort the roll instead of feeding replica 2 different bytes.
    bit_flip_file(os.path.join(export, "train_state", "l00000.bin"))
    for _ in range(50):
        if rep._reload is None:
            break
        rep.step()
    assert rep._reload is None
    assert rep.last_reload_ok is False
    # The fleet still serves.
    sp_out = rep.generate([[1, 2, 3]], None)
    assert sp_out[0].output_token_ids


# ----------------------------------------------------------------------
# watchdog canary_regression rule
# ----------------------------------------------------------------------

def _watchdog(sampler, **over):
    kw = dict(enabled=True, interval_s=0.05, hung_step_min_s=30.0)
    kw.update(over)
    return AnomalyWatchdog(WatchdogConfig(**kw), sampler,
                           tracer=SpanTracer(enabled=False),
                           clock=time.monotonic)


def test_canary_regression_rule_fires_on_rollback_growth():
    s = TimeSeriesSampler(capacity=16)
    state = {"rb": 0.0}
    s.add_source(lambda: {"dlti_deploy_rollbacks_total": state["rb"]})
    wd = _watchdog(s, canary_regression_limit=1)
    s.sample_now()
    assert wd.check_now() == []  # watermark established
    state["rb"] = 1.0
    s.sample_now()
    fired = wd.check_now()
    assert [a["rule"] for a in fired] == ["canary_regression"]
    assert "rolled back" in fired[0]["message"]
    s.sample_now()
    assert wd.check_now() == []  # flat: re-armed quietly
    state["rb"] = 3.0
    s.sample_now()
    assert [a["rule"] for a in wd.check_now()] == ["canary_regression"]


def test_canary_regression_rule_disabled_by_zero_limit():
    s = TimeSeriesSampler(capacity=16)
    state = {"rb": 0.0}
    s.add_source(lambda: {"dlti_deploy_rollbacks_total": state["rb"]})
    wd = _watchdog(s, canary_regression_limit=0)
    s.sample_now()
    wd.check_now()
    state["rb"] = 4.0
    s.sample_now()
    assert wd.check_now() == []


# ----------------------------------------------------------------------
# flight recorder: deploy.json in every dump
# ----------------------------------------------------------------------

def test_flight_dump_carries_deploy_state(tmp_path):
    from dlti_tpu.telemetry.flightrecorder import (
        FlightRecorder, verify_dump,
    )

    ctrl, fleet, clk, watch, engines = _controller(tmp_path)
    rec = FlightRecorder(str(tmp_path / "flight"),
                         tracer=SpanTracer(enabled=False))
    rec.add_deploy_source(ctrl.to_dict)
    path = rec.dump(reason="test")
    assert path is not None
    assert verify_dump(path) == []
    with open(os.path.join(path, "deploy.json")) as f:
        dep = json.load(f)
    assert dep["state"] == "idle"
    assert dep["incumbent"]["step"] == -1
    assert "counters" in dep


# ----------------------------------------------------------------------
# /v1/deploy server surface
# ----------------------------------------------------------------------

def test_v1_deploy_endpoint_status_and_toggle(tmp_path):
    import jax
    import jax.numpy as jnp

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine
    from dlti_tpu.serving.server import ServerConfig, make_server

    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = InferenceEngine(
        cfg, params,
        EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                     max_model_len=64, cache_dtype="float32",
                     eos_token_id=-1))
    # The controller watches nothing (empty watch dir) — the HTTP test
    # only exercises the operator surface.
    ctrl = DeploymentController(
        FakeFleet(),
        DeployConfig(enabled=True, watch_dir="",
                     export_dir=str(tmp_path / "exports")))
    httpd, async_engine = make_server(
        engine, IdTokenizer(vocab_size=cfg.vocab_size),
        ServerConfig(host="127.0.0.1", port=0), deploy=ctrl)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("GET", "/v1/deploy")
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200
        assert body["enabled"] is True and body["state"] == "idle"

        conn.request("POST", "/v1/deploy",
                     json.dumps({"enabled": False}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body["enabled"] is False
        assert ctrl.enabled is False

        conn.request("POST", "/v1/deploy", json.dumps({}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 400

        conn.request("POST", "/v1/deploy",
                     json.dumps({"enabled": True}),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        body = json.loads(resp.read())
        assert resp.status == 200 and body["enabled"] is True
        conn.close()
    finally:
        httpd.shutdown()
        ctrl.stop()
        async_engine.shutdown()
        httpd.server_close()


def test_v1_deploy_404_without_controller(tmp_path):
    import jax
    import jax.numpy as jnp

    from dlti_tpu.config import MODEL_PRESETS
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine
    from dlti_tpu.serving.server import ServerConfig, make_server

    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    engine = InferenceEngine(
        cfg, params,
        EngineConfig(max_seqs=2, block_size=8, num_blocks=32,
                     max_model_len=64, cache_dtype="float32",
                     eos_token_id=-1))
    httpd, async_engine = make_server(
        engine, IdTokenizer(vocab_size=cfg.vocab_size),
        ServerConfig(host="127.0.0.1", port=0))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        for method, body in (("GET", None),
                             ("POST", json.dumps({"enabled": False}))):
            conn.request(method, "/v1/deploy", body,
                         {"Content-Type": "application/json"}
                         if body else {})
            resp = conn.getresponse()
            resp.read()
            assert resp.status == 404
        conn.close()
    finally:
        httpd.shutdown()
        async_engine.shutdown()
        httpd.server_close()


# ----------------------------------------------------------------------
# export_params_host: the exporter behind the watch loop
# ----------------------------------------------------------------------

def test_export_params_host_roundtrip_and_corruption(tmp_path):
    import jax.numpy as jnp
    import optax
    from flax.training.train_state import TrainState

    from dlti_tpu.checkpoint import export_params_host
    from dlti_tpu.checkpoint.store import save_train_state

    params = {"a": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
              "b": np.ones((3,), np.float32)}
    state = TrainState.create(apply_fn=lambda *a, **k: None,
                              params=jax.tree_util.tree_map(jnp.asarray,
                                                            params),
                              tx=optax.sgd(0.1))
    ckpt = str(tmp_path / "ckpt")
    save_train_state(ckpt, 3, state, async_save=False)

    out = str(tmp_path / "export")
    digest = export_params_host(ckpt, 3, out)
    assert digest == manifest_digest(out)
    back = load_pytree(out, verify=True)
    np.testing.assert_array_equal(back["a"]["w"], params["a"]["w"])
    np.testing.assert_array_equal(back["b"], params["b"])

    # A corrupt source checkpoint raises instead of exporting garbage.
    from dlti_tpu.checkpoint import CheckpointCorruptError
    from dlti_tpu.checkpoint.chaos import bit_flip_file

    # Flip a byte in a .params leaf specifically — the export ignores
    # optimizer-state leaves, so damage there wouldn't (and needn't)
    # trip the params integrity check.
    with open(os.path.join(ckpt, "3", "MANIFEST.json")) as f:
        manifest = json.load(f)
    victim = next(e["file"] for e in manifest["leaves"]
                  if e["name"].startswith(".params["))
    bit_flip_file(os.path.join(ckpt, "3", victim))
    with pytest.raises(CheckpointCorruptError):
        export_params_host(ckpt, 3, str(tmp_path / "export2"))
