"""Sanitizer-analog utilities: sharding assertions, finite checks, and
deterministic step replay (SURVEY.md §5.2 — the reference has nothing
here; DDP's unused-parameter detection is even turned off,
``train_deepspeed_zero1.py:248``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dlti_tpu.config import (
    Config, LoRAConfig, MODEL_PRESETS, OptimizerConfig, ParallelConfig,
    TrainConfig, ZeROStage,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.parallel import build_mesh, shard_train_state
from dlti_tpu.parallel.sharding import state_shardings
from dlti_tpu.training import build_optimizer, create_train_state, make_train_step
from dlti_tpu.utils.debug import (
    StepRecorder,
    assert_all_finite,
    assert_tree_sharding,
    replay_step,
    sharding_mismatches,
)


def _sharded_state(rng, zero=ZeROStage.ZERO3):
    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
        parallel=ParallelConfig(zero_stage=zero, fsdp=4, tensor=2),
        train=TrainConfig(micro_batch_size=4, grad_accum_steps=1),
    )
    mesh = build_mesh(cfg.parallel)
    model = LlamaForCausalLM(cfg.model, cfg.lora, mesh)
    tx = build_optimizer(cfg.optimizer)
    state = create_train_state(rng, model, tx, (4, 32), lora_enabled=True)
    state = shard_train_state(state, cfg, mesh)
    return cfg, mesh, state


@pytest.mark.slow
def test_sharding_assertion_passes_on_intended_layout(rng):
    cfg, mesh, state = _sharded_state(rng)
    expected = state_shardings(state, cfg, mesh)
    assert sharding_mismatches(state.params, expected.params) == []
    assert_tree_sharding(state.params, expected.params, what="params")


def test_sharding_assertion_names_drifted_leaves(rng):
    cfg, mesh, state = _sharded_state(rng)
    expected = state_shardings(state, cfg, mesh)
    # Re-place one leaf with a wrong (fully replicated) sharding.
    bad_params = jax.tree_util.tree_map(lambda x: x, state.params)
    leaf = bad_params["model"]["embed_tokens"]
    bad_params["model"]["embed_tokens"] = jax.device_put(
        leaf, NamedSharding(mesh, P()))
    bad = sharding_mismatches(bad_params, expected.params)
    assert any("embed_tokens" in p for p, _, _ in bad)
    with pytest.raises(AssertionError, match="embed_tokens"):
        assert_tree_sharding(bad_params, expected.params)


def test_assert_all_finite_names_bad_leaf():
    tree = {"ok": jnp.ones((4,)), "bad": jnp.array([1.0, np.nan, np.inf])}
    with pytest.raises(AssertionError, match="bad: 2/3"):
        assert_all_finite(tree)
    assert_all_finite({"ok": jnp.ones((4,))})  # no raise


def test_step_recorder_roundtrip_and_rotation(tmp_path):
    rec = StepRecorder(str(tmp_path), keep=2, every_steps=1)
    rng = jax.random.PRNGKey(3)
    for s in (1, 2, 3):
        batch = {"input_ids": np.full((1, 2, 8), s, np.int32)}
        rec.record(s, batch, rng, {"loss": 1.0 / s})
    import os

    files = sorted(os.listdir(tmp_path))
    assert files == ["step_00000002.npz", "step_00000003.npz"]  # rotated
    step, batch, r, metrics = StepRecorder.load(str(tmp_path / files[-1]))
    assert step == 3 and batch["input_ids"][0, 0, 0] == 3
    assert metrics["loss"] == pytest.approx(1 / 3)
    np.testing.assert_array_equal(jax.random.key_data(r),
                                  jax.random.key_data(rng))


@pytest.mark.slow
def test_replay_reproduces_recorded_step(tmp_path, rng):
    """Record a live step, then re-execute it: bitwise-equal metrics."""
    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, LoRAConfig(r=4, alpha=8, dropout=0.0))
    tx = build_optimizer(OptimizerConfig())
    state = create_train_state(rng, model, tx, (2, 32))
    step = jax.jit(make_train_step(model, accum_steps=1))
    batch = {"input_ids": np.asarray(
        jax.random.randint(rng, (1, 2, 32), 0, cfg.vocab_size)),
        "loss_mask": np.ones((1, 2, 32), np.int32)}
    step_rng = jax.random.fold_in(rng, 7)
    _, metrics = step(state, batch, step_rng)
    metrics = {k: float(jax.device_get(v)) for k, v in metrics.items()}

    rec = StepRecorder(str(tmp_path))
    rec.record(1, batch, step_rng, metrics)
    replayed = replay_step(str(tmp_path / "step_00000001.npz"), step, state,
                           rtol=0.0)
    assert replayed["loss"] == metrics["loss"]


@pytest.mark.slow
def test_replay_detects_divergence(tmp_path, rng):
    """A replay against the wrong state must fail loudly."""
    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, LoRAConfig(r=4, alpha=8, dropout=0.0))
    tx = build_optimizer(OptimizerConfig())
    state = create_train_state(rng, model, tx, (2, 32))
    step = jax.jit(make_train_step(model, accum_steps=1))
    batch = {"input_ids": np.asarray(
        jax.random.randint(rng, (1, 2, 32), 0, cfg.vocab_size)),
        "loss_mask": np.ones((1, 2, 32), np.int32)}
    step_rng = jax.random.fold_in(rng, 7)
    _, metrics = step(state, batch, step_rng)
    metrics = {k: float(jax.device_get(v)) for k, v in metrics.items()}
    rec = StepRecorder(str(tmp_path))
    rec.record(1, batch, step_rng, metrics)

    other_state = create_train_state(jax.random.PRNGKey(99), model, tx, (2, 32))
    with pytest.raises(AssertionError, match="diverged"):
        replay_step(str(tmp_path / "step_00000001.npz"), step, other_state,
                    rtol=0.0)


def test_trainer_records_replay_ring(tmp_path, rng):
    """The Trainer wiring: record_replay_dir fills a ring during train()."""
    import os

    from dlti_tpu.data.pipeline import TokenBatchDataset
    from dlti_tpu.training import Trainer

    from dlti_tpu.config import CheckpointConfig

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
        train=TrainConfig(micro_batch_size=2, grad_accum_steps=1, max_steps=4,
                          record_replay_dir=str(tmp_path / "replay"),
                          record_replay_every=2, record_replay_keep=2,
                          metrics_csv=str(tmp_path / "m.csv")),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_strategy="no"),
    )
    ds = TokenBatchDataset(
        sequences=[[1, 2, 3, 4]] * 16, seq_len=32, pad_id=0,
        micro_batch_size=2, grad_accum_steps=1, shard_by_host=False)
    trainer = Trainer(cfg)
    trainer.train(dataset=ds, resume=False)
    files = sorted(os.listdir(tmp_path / "replay"))
    assert files == ["step_00000002.npz", "step_00000004.npz"]
