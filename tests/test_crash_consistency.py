"""Crash-consistent checkpointing: atomic commit, digest verification,
quarantine + fallback resume, exact-state resume, fault injection.

The tier-1 half of the chaos story (the subprocess SIGKILL drills live in
``tests/test_crash_smoke.py``, slow tier): every on-disk failure mode a
kill or bad disk can produce — torn staging dirs, truncated files, bit
flips, missing commit markers — is fabricated deterministically via
``dlti_tpu.checkpoint.chaos`` and must be quarantined (renamed, counted,
logged) with resume falling back to the newest checkpoint that proves
out; and a mid-epoch resume must replay a **bit-identical** loss
trajectory versus the uninterrupted run (weights + data cursor + rng
schedule all restored).
"""

import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlti_tpu.checkpoint import (
    CheckpointCorruptError,
    latest_step,
    latest_verified_step,
    list_checkpoint_steps,
    load_train_meta,
    restore_latest_verified,
    restore_train_state,
    save_train_state,
    verify_checkpoint,
    wait_for_saves,
)
from dlti_tpu.checkpoint.chaos import (
    CORRUPT_MODES,
    corrupt_checkpoint,
    make_torn_save,
)
from dlti_tpu.checkpoint.store import corrupt_skipped, save_retries
from dlti_tpu.config import (
    CheckpointConfig, Config, DataConfig, LoRAConfig, MODEL_PRESETS,
    OptimizerConfig, ParallelConfig, TelemetryConfig, TrainConfig,
)
from dlti_tpu.data import TokenBatchDataset
from dlti_tpu.training.chaos import TrainFault, TrainFaultInjector

CFG = MODEL_PRESETS["llama_tiny"]


# ----------------------------------------------------------------------
# Store unit contracts (no Trainer, no jit-heavy work)
# ----------------------------------------------------------------------

def _tree(seed=0):
    k = jax.random.PRNGKey(seed)
    return {
        "w": jax.random.normal(k, (4, 3), jnp.float32),
        "b": {"scale": jnp.arange(3, dtype=jnp.bfloat16),
              "count": jnp.array(7 + seed, jnp.int32)},
    }


def test_save_restore_roundtrip_and_sidecar(tmp_path):
    d = str(tmp_path)
    save_train_state(d, 2, _tree(0), keep=3, async_save=True,
                     train_meta={"step": 2, "epoch": 0})
    save_train_state(d, 5, _tree(1), keep=3, async_save=True,
                     train_meta={"step": 5, "epoch": 1})
    wait_for_saves(d)
    assert list_checkpoint_steps(d) == [2, 5]
    assert latest_step(d) == 5
    assert latest_verified_step(d) == 5
    assert verify_checkpoint(d, 5) == (True, "ok")
    target = jax.tree_util.tree_map(jnp.zeros_like, _tree(0))
    out = restore_train_state(d, 5, target)
    want = _tree(1)
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(want["w"]))
    assert out["b"]["scale"].dtype == jnp.bfloat16
    assert int(out["b"]["count"]) == 8
    assert load_train_meta(d, 5) == {"step": 5, "epoch": 1}
    # Committed layout: commit marker present, no staging dirs left.
    assert os.path.isfile(tmp_path / "5" / "COMMIT")
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]


def test_duplicate_save_is_idempotent(tmp_path):
    d = str(tmp_path)
    save_train_state(d, 3, _tree(0), async_save=False)
    save_train_state(d, 3, _tree(1), async_save=False)  # resumed re-save
    out = restore_train_state(d, 3, _tree(0))
    np.testing.assert_array_equal(np.asarray(out["w"]),
                                  np.asarray(_tree(0)["w"]))


def test_rotation_keeps_newest(tmp_path):
    d = str(tmp_path)
    for step in (1, 2, 3, 4):
        save_train_state(d, step, _tree(step), keep=2, async_save=False)
    assert list_checkpoint_steps(d) == [3, 4]


@pytest.mark.parametrize("mode", CORRUPT_MODES)
def test_corruption_quarantined_with_fallback(tmp_path, mode):
    """Every damage mode on the newest checkpoint: the resume scan must
    quarantine it (renamed + counted) and fall back to the older good
    one — never crash, never trust the bad bytes."""
    d = str(tmp_path)
    save_train_state(d, 2, _tree(0), async_save=False,
                     train_meta={"step": 2})
    save_train_state(d, 4, _tree(1), async_save=False,
                     train_meta={"step": 4})
    corrupt_checkpoint(d, 4, mode)
    before = corrupt_skipped.value
    target = jax.tree_util.tree_map(jnp.zeros_like, _tree(0))
    got = restore_latest_verified(d, target)
    assert got is not None
    state, step, meta = got
    assert step == 2 and meta == {"step": 2}
    np.testing.assert_array_equal(np.asarray(state["w"]),
                                  np.asarray(_tree(0)["w"]))
    assert corrupt_skipped.value > before
    assert os.listdir(tmp_path / "_quarantine")
    # The damaged step no longer shows up as committed.
    assert list_checkpoint_steps(d) == [2]


def test_torn_async_save_is_quarantined(tmp_path):
    """The wreckage of a kill mid-async-save (a ``.tmp-*`` staging dir,
    no manifest/commit) must be swept into quarantine by the scan."""
    d = str(tmp_path)
    save_train_state(d, 2, _tree(0), async_save=False)
    make_torn_save(d, 4)
    assert [n for n in os.listdir(d) if n.startswith(".tmp-")]
    assert latest_verified_step(d) == 2
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]
    assert os.listdir(tmp_path / "_quarantine")


def test_all_checkpoints_corrupt_returns_none(tmp_path):
    d = str(tmp_path)
    save_train_state(d, 2, _tree(0), async_save=False)
    corrupt_checkpoint(d, 2, "bitflip-array")
    target = jax.tree_util.tree_map(jnp.zeros_like, _tree(0))
    assert restore_latest_verified(d, target) is None


def test_save_retries_transient_failure(tmp_path):
    """Transient I/O faults during a save retry with backoff and the
    checkpoint still lands — healed *below* the store by the durable
    writer (ledger-counted) while they fit its budget; exhausting that
    budget escapes to the store's staging-cycle retry loop
    (``dlti_ckpt_save_retries``), which restages and commits."""
    from dlti_tpu.checkpoint.chaos import FaultyIO
    from dlti_tpu.utils import durable_io

    durable_io.reset_for_tests()
    try:
        # 2 EIOs: absorbed by the durable writer's own transient retry.
        before = save_retries.value
        with FaultyIO.from_spec(f"{tmp_path}{os.sep}.tmp-2-*:EIO:2"):
            save_train_state(str(tmp_path), 2, _tree(0), async_save=False,
                             retries=3, retry_backoff_s=0.01)
        assert save_retries.value == before  # never reached the store loop
        assert durable_io.disk_ledger()["checkpoint"]["errors"] == 2
        assert verify_checkpoint(str(tmp_path), 2) == (True, "ok")

        # 4 EIOs on one op: the checkpoint class's durable budget (3
        # retries = 4 attempts) exhausts, the store books a save retry
        # and restages into a fresh .tmp-* — the commit still lands.
        with FaultyIO.from_spec(f"{tmp_path}{os.sep}.tmp-3-*:EIO:4"):
            save_train_state(str(tmp_path), 3, _tree(1), async_save=False,
                             retries=3, retry_backoff_s=0.01)
        assert save_retries.value == before + 1
        assert verify_checkpoint(str(tmp_path), 3) == (True, "ok")
        assert not [n for n in os.listdir(tmp_path)
                    if n.startswith(".tmp-")]
    finally:
        durable_io.reset_for_tests()


def test_save_failure_is_bounded_and_never_raises_on_wait(tmp_path,
                                                          monkeypatch):
    """Retries exhausted: the async writer logs the error; wait_for_saves
    returns (training must outlive a dead checkpoint disk)."""
    import dlti_tpu.checkpoint.store as store

    def always_fail(tmp, p):
        raise OSError("disk on fire")

    monkeypatch.setattr(store, "_write_staging", always_fail)
    save_train_state(str(tmp_path), 2, _tree(0), async_save=True,
                     retries=1, retry_backoff_s=0.01)
    wait_for_saves(str(tmp_path))  # must not raise
    assert list_checkpoint_steps(str(tmp_path)) == []


def test_restore_structure_mismatch_raises_value_error(tmp_path):
    d = str(tmp_path)
    save_train_state(d, 2, _tree(0), async_save=False)
    with pytest.raises(ValueError, match="leaves|structure"):
        restore_train_state(d, 2, {"only": jnp.zeros((2,))})
    bad_shape = jax.tree_util.tree_map(jnp.zeros_like, _tree(0))
    bad_shape["w"] = jnp.zeros((5, 5), jnp.float32)
    with pytest.raises(ValueError, match="expects"):
        restore_train_state(d, 2, bad_shape)


def test_truncated_array_raises_corrupt_not_garbage(tmp_path):
    from dlti_tpu.checkpoint.chaos import truncate_file

    d = str(tmp_path)
    save_train_state(d, 2, _tree(0), async_save=False)
    truncate_file(os.path.join(d, "2", "train_state", "l00000.bin"))
    with pytest.raises(CheckpointCorruptError):
        restore_train_state(d, 2, _tree(0))


def test_export_pytree_verify_detects_corruption(tmp_path):
    from dlti_tpu.checkpoint.chaos import bit_flip_file
    from dlti_tpu.checkpoint.store import load_pytree, save_pytree

    p = {"m": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)}}
    d = save_pytree(str(tmp_path / "model"), p)
    back = load_pytree(d, verify=True)
    np.testing.assert_array_equal(back["m"]["w"], p["m"]["w"])
    bit_flip_file(os.path.join(d, "train_state", "l00000.bin"))
    with pytest.raises(CheckpointCorruptError):
        load_pytree(d, verify=True)


def test_fault_injector_spec_parsing(monkeypatch):
    assert TrainFaultInjector.from_spec("") is None
    fi = TrainFaultInjector.from_spec("7")
    assert (fi.step, fi.mode) == (7, "raise")
    fi = TrainFaultInjector.from_spec("3:save-kill")
    assert (fi.step, fi.mode) == (3, "save-kill")
    monkeypatch.setenv("DLTI_TRAIN_FAULT_INJECT", "5:kill")
    fi = TrainFaultInjector.from_spec(None)
    assert (fi.step, fi.mode) == (5, "kill")
    with pytest.raises(ValueError, match="mode"):
        TrainFaultInjector.from_spec("5:explode")
    with pytest.raises(ValueError, match="spec"):
        TrainFaultInjector.from_spec("soon")
    fi = TrainFaultInjector.from_spec("2:raise")
    with pytest.raises(TrainFault):
        fi.maybe_fire_step(2)
    fi.maybe_fire_step(3)  # fires at most once


# ----------------------------------------------------------------------
# Trainer-level: exact-state resume + recovery end to end
# ----------------------------------------------------------------------

def _dataset(pack=False, n=96, seq_len=16):
    rng = np.random.default_rng(11)
    seqs = [list(map(int, rng.integers(1, 500,
                                       size=int(rng.integers(6, 12)))))
            for _ in range(n)]
    return TokenBatchDataset(sequences=seqs, seq_len=seq_len, pad_id=0,
                             micro_batch_size=2, grad_accum_steps=1,
                             shard_by_host=False, pack=pack)


def _cfg(tmp_path, tag, max_steps, save_steps=1000, save_strategy="steps",
         async_save=True, fault=""):
    return Config(
        model=CFG, lora=LoRAConfig(r=2, alpha=4, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(),
        data=DataConfig(max_seq_len=16, prefetch_depth=2),
        train=TrainConfig(num_epochs=1, max_steps=max_steps,
                          micro_batch_size=2, grad_accum_steps=1,
                          logging_steps=1000, fault_inject_step=fault,
                          metrics_csv=str(tmp_path / f"{tag}.csv")),
        checkpoint=CheckpointConfig(output_dir=str(tmp_path / "ckpt"),
                                    save_strategy=save_strategy,
                                    save_steps=save_steps,
                                    save_total_limit=3,
                                    async_save=async_save),
        telemetry=TelemetryConfig(
            step_log_path=str(tmp_path / f"{tag}.jsonl")),
    )


def _losses(tmp_path, tag):
    rows = [json.loads(line) for line in open(tmp_path / f"{tag}.jsonl")]
    return {r["step"]: r["loss"] for r in rows if r.get("type") == "step"}


@pytest.mark.parametrize("pack", [False, True])
def test_midepoch_resume_bit_identical_losses(tmp_path, pack):
    """The acceptance bar: weights + data cursor + rng schedule all
    restore, so steps replayed after a mid-epoch resume produce the exact
    float losses of the uninterrupted run — equality, not closeness."""
    from dlti_tpu.training.trainer import Trainer

    sub = tmp_path / f"pack{pack}"
    sub.mkdir()
    ref_cfg = _cfg(sub, "ref", max_steps=6, save_strategy="no")
    Trainer(ref_cfg).train(dataset=_dataset(pack))
    ref = _losses(sub, "ref")
    assert len(ref) == 6

    half_cfg = _cfg(sub, "half", max_steps=3, save_steps=3)
    Trainer(half_cfg).train(dataset=_dataset(pack))
    assert latest_verified_step(str(sub / "ckpt")) == 3
    # The sidecar carries the data cursor + rng schedule.
    meta = load_train_meta(str(sub / "ckpt"), 3)
    assert meta["step"] == 3 and meta["rng_schedule"] == "fold_in_v1"
    assert meta["dataset"]["steps_per_epoch"] > 0
    assert meta["dataset"]["packed"] == pack

    rest_cfg = _cfg(sub, "rest", max_steps=6, save_steps=1000)
    state, _ = Trainer(rest_cfg).train(dataset=_dataset(pack))
    assert int(jax.device_get(state.step)) == 6
    got = _losses(sub, "rest")
    assert set(got) == {4, 5, 6}
    for s in (4, 5, 6):
        assert got[s] == ref[s], (s, got[s], ref[s])


def test_streaming_dataset_resume_bit_identical(tmp_path):
    """Same exactness bar against the disk-backed token store."""
    from dlti_tpu.data.streaming import StreamingTokenDataset, \
        write_token_store
    from dlti_tpu.training.trainer import Trainer

    rng = np.random.default_rng(13)
    docs = [list(map(int, rng.integers(1, 400,
                                       size=int(rng.integers(5, 10)))))
            for _ in range(48)]
    store_dir = str(tmp_path / "store")
    write_token_store(iter(docs), store_dir, seq_len=16, pad_id=0)

    def ds():
        return StreamingTokenDataset(store_dir, micro_batch_size=2,
                                     grad_accum_steps=1,
                                     shard_by_host=False)

    ref_cfg = _cfg(tmp_path, "sref", max_steps=6, save_strategy="no")
    Trainer(ref_cfg).train(dataset=ds())
    ref = _losses(tmp_path, "sref")

    half_cfg = _cfg(tmp_path, "shalf", max_steps=3, save_steps=3)
    Trainer(half_cfg).train(dataset=ds())
    rest_cfg = _cfg(tmp_path, "srest", max_steps=6)
    Trainer(rest_cfg).train(dataset=ds())
    got = _losses(tmp_path, "srest")
    for s in (4, 5, 6):
        assert got[s] == ref[s]


def test_kill_mid_async_save_falls_back_bit_identical(tmp_path):
    """A run killed mid-async-save leaves a torn staging dir; resume must
    quarantine it, restore the newest *verified* step, and replay to a
    bit-identical trajectory (the PR's acceptance criterion, in-process;
    the real-SIGKILL version runs in the slow smoke tier)."""
    from dlti_tpu.training.trainer import Trainer

    ref_cfg = _cfg(tmp_path, "kref", max_steps=6, save_strategy="no")
    Trainer(ref_cfg).train(dataset=_dataset(False))
    ref = _losses(tmp_path, "kref")

    half_cfg = _cfg(tmp_path, "khalf", max_steps=4, save_steps=2)
    Trainer(half_cfg).train(dataset=_dataset(False))
    ckpt = str(tmp_path / "ckpt")
    assert latest_step(ckpt) == 4
    # Simulate the kill landing while step 4's async save was mid-write:
    # demote the committed dir to the torn staging dir a SIGKILL leaves.
    corrupt_checkpoint(ckpt, 4, "stale-tmp")
    before = corrupt_skipped.value

    rest_cfg = _cfg(tmp_path, "krest", max_steps=6)
    state, _ = Trainer(rest_cfg).train(dataset=_dataset(False))
    assert int(jax.device_get(state.step)) == 6
    got = _losses(tmp_path, "krest")
    # Resumed from step 2 (newest verified), replayed 3..6 exactly.
    assert set(got) == {3, 4, 5, 6}
    for s in (3, 4, 5, 6):
        assert got[s] == ref[s]
    assert corrupt_skipped.value > before


def test_trainer_crash_cleans_up_and_resumes(tmp_path):
    """Fault injection 'raise' mode: the exception propagates, the
    prefetch worker is shut down (no leaked thread), in-flight async
    saves are settled by the finally (no stranded staging dir), and a
    fresh Trainer resumes and finishes with the uninterrupted losses."""
    from dlti_tpu.training.trainer import Trainer

    ref_cfg = _cfg(tmp_path, "cref", max_steps=6, save_strategy="no")
    Trainer(ref_cfg).train(dataset=_dataset(False))
    ref = _losses(tmp_path, "cref")

    crash_cfg = _cfg(tmp_path, "crash", max_steps=6, save_steps=2,
                     fault="3:raise")
    with pytest.raises(TrainFault):
        Trainer(crash_cfg).train(dataset=_dataset(False))
    # Prefetch worker joined on the exception path.
    assert not [t for t in threading.enumerate()
                if t.name.startswith("dlti-prefetch")]
    ckpt = str(tmp_path / "ckpt")
    # The finally settled the async save of step 2 — committed, not torn.
    assert [n for n in os.listdir(ckpt) if n.startswith(".tmp-")] == []
    assert latest_verified_step(ckpt) == 2

    rest_cfg = _cfg(tmp_path, "crest", max_steps=6)
    state, _ = Trainer(rest_cfg).train(dataset=_dataset(False))
    assert int(jax.device_get(state.step)) == 6
    got = _losses(tmp_path, "crest")
    for s in (3, 4, 5, 6):
        assert got[s] == ref[s]
