"""CI smoke for the flash-crowd SLO drill (satellite of the SLO-engine
PR), mirroring tests/test_disagg_bench.py: the artifact generator behind
``results/slo_drill_cpu.json`` must stay runnable, and its claim must
hold on a cold CPU run — the watchdog's ``slo_burn`` rule pages *before*
the error budget is exhausted, the burst costs latency but zero client
errors, and loadgen's client-side SLO recomputation agrees with the
server's ``GET /debug/slo`` within 1% per (objective, class) pair. The
committed artifact (default 40s warm phase on a quiet machine) is the
PR's evidence; the smoke runs a shortened warm phase and pins the same
criteria."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks_dev", "slo_drill.py")


@pytest.mark.slow
def test_slo_drill_smoke(tmp_path):
    out = tmp_path / "slo_drill.json"
    trace = tmp_path / "slo_drill_trace.jsonl"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, BENCH, "--warm-s", "12", "--flash-duration-s",
         "3", "--json-out", str(out), "--trace-out", str(trace)],
        env=env, capture_output=True, text=True, timeout=560, cwd=REPO)
    # The drill asserts its own criteria before exiting 0.
    assert proc.returncode == 0, (proc.stdout[-800:], proc.stderr[-800:])
    report = json.loads(out.read_text())
    assert report["pass"] is True
    assert all(report["criteria"].values()), report["criteria"]
    assert report["alerts"]["slo_burn_count"] >= 1
    assert report["alerts"]["first_alert"]["budget_remaining"] > 0.0
    assert report["load"]["num_ok"] == report["load"]["num_requests"]
    assert report["slo"]["max_delta"] <= 0.01
    # The replayed trace is itself a valid fixture.
    from dlti_tpu.benchmarks.traces import read_trace

    header, events = read_trace(str(trace))
    assert header["generator"] == "flash_crowd"
    assert len(events) == report["load"]["num_requests"]


def test_committed_artifact_meets_the_bar():
    """The checked-in results/slo_drill_cpu.json is the PR's evidence;
    pin the acceptance bar so a regenerated artifact that misses it
    fails CI instead of silently shipping."""
    path = os.path.join(REPO, "results", "slo_drill_cpu.json")
    report = json.loads(open(path).read())
    assert report["pass"] is True
    c = report["criteria"]
    assert c["alert_fired"] and c["budget_remained_at_first_alert"]
    assert c["zero_client_errors"] and c["slo_agreement_within_1pct"]
    # The page landed early: well over half the budget was still there.
    assert report["alerts"]["first_alert"]["budget_remaining"] > 0.05
    assert report["alerts"]["first_alert"]["objective"] == "ttft"
    assert report["slo"]["max_delta"] <= 0.01
    assert report["load"]["errors"] == []
    # The committed trace replays to exactly the recorded request count.
    from dlti_tpu.benchmarks.traces import read_trace

    tpath = os.path.join(REPO, "results",
                         report["workload"]["trace_file"])
    header, events = read_trace(tpath)
    assert header["num_events"] == len(events) == \
        report["load"]["num_requests"]
