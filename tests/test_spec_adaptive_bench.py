"""CI contract for the adaptive-speculation A/B bench (satellite of the
adaptive-spec PR), mirroring tests/test_multilora_bench.py: the artifact
generator behind ``results/spec_adaptive_cpu.json`` must stay runnable
with its compile-warmup methodology intact, and its equivalence claims
must hold on a cold run — every arm byte-identical to plain greedy
before a number is written. Throughput margins are properties of the
committed artifact (quiet machine), not of this noisy smoke, so the
smoke pins shape + equivalence; the artifact test pins the bars."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "benchmarks_dev", "spec_win.py")


@pytest.mark.slow
def test_spec_adaptive_bench_smoke(tmp_path):
    out = tmp_path / "spec_adaptive_cpu.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, BENCH, "--cpu", "--runs", "1", "--max-tokens",
         "48", "--wave", "8", "--json-out", str(out)],
        env=env, capture_output=True, text=True, timeout=600, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-1500:]
    report = json.loads(out.read_text())

    # The bench asserts per-arm output equality before writing; the
    # report must record it for every arm.
    assert report["outputs_equal"] is True
    for trace in ("favorable", "adversarial"):
        assert report[trace]["outputs_equal"] is True
        assert len(report[trace]["plain_tok_s_all"]) == 1
        assert len(report[trace]["spec_tok_s_all"]) == 1
    # The favorable trace genuinely speculated on this cold run.
    assert report["favorable"]["draft_acceptance"] > 0.5
    assert report["ragged_prefill"]["outputs_equal"] is True
    for key in ("what", "platform", "steps_per_sync", "num_draft_tokens",
                "favorable", "adversarial", "ragged_prefill", "date"):
        assert key in report, key


def test_committed_artifact_meets_the_bar():
    """The checked-in results/spec_adaptive_cpu.json is the PR's
    evidence; pin the acceptance bars (≥20% favorable win, ≤5%
    adversarial regression with the gate on, outputs_equal every arm,
    ragged TTFT p99 no worse than bucketed) so a regenerated artifact
    that misses them fails CI instead of silently shipping — the r03
    artifact this replaces recorded a 0.103 "speedup" measured across
    in-window XLA compiles."""
    path = os.path.join(REPO, "results", "spec_adaptive_cpu.json")
    report = json.loads(open(path).read())
    assert report["outputs_equal"] is True
    fav, adv = report["favorable"], report["adversarial"]
    assert fav["outputs_equal"] is True and adv["outputs_equal"] is True
    assert len(fav["plain_tok_s_all"]) >= 3  # median-of-3 methodology
    assert fav["speedup"] >= 1.2
    assert fav["draft_acceptance"] >= 0.5
    assert adv["speedup"] >= 0.95
    # The adversarial trace exercised the gate, not an accidental win.
    assert adv["spec_paused_rounds"] > 0
    rag = report["ragged_prefill"]
    assert rag["outputs_equal"] is True
    assert rag["ttft_p99_s_on"] <= rag["ttft_p99_s_off"]
    assert rag["prefill_batches_on"] < rag["prefill_batches_off"]
