"""Multi-process serving fleet tests (dlti_tpu.serving.fleet).

Layers:

* **Thread-spawner fast tier** — the spawner seam injects in-process
  ``EngineWorker`` threads instead of real processes, so the full
  supervisor ↔ worker wire conversation (submit / step / drain / adopt /
  health / abort) runs in seconds:
  - byte-identity with a single-process engine (greedy and seeded),
  - cross-worker KV-handoff migration on drain, byte-identical, bf16 and
    int8 KV (the envelope's numpy payloads round-trip byte-exactly),
  - kill → failover + canary-gated respawn with zero client errors and
    monotonic per-worker counters,
  - a worker that survives garbage/truncated/oversized/corrupt frames
    and still answers a clean health round-trip,
  - an evil peer speaking corrupt frames: the supervisor evicts it and
    rehomes its work instead of hanging or corrupting an adoption,
  - the ReplicatedEngine-compatible facade + federation arithmetic
    (per-worker counter sums == fleet totals; loadgen's key mirror).
* **Subprocess slow tier** — the real ``scripts/engine_worker.py``
  drill: ``--fleet-workers 2`` outputs byte-identical to an in-process
  2-replica engine (greedy + seeded, incl. one cross-process migration),
  and a live-loadgen chaos drill that SIGKILLs a worker mid-run and
  demands zero client errors, a respawn, and consistent federated
  metrics.
"""

import contextlib
import dataclasses
import http.client
import itertools
import json
import os
import signal
import socket
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from dlti_tpu.config import (
    FleetConfig, GatewayConfig, MODEL_PRESETS, ReplicaLifecycleConfig,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import (
    EngineConfig, InferenceEngine, ReplicatedEngine, SamplingParams,
)
from dlti_tpu.serving import fleet, wire
from dlti_tpu.serving.engine import Request
from dlti_tpu.serving.fleet import FleetSupervisor, make_subprocess_spawner
from dlti_tpu.serving.worker import EngineWorker

CFG = MODEL_PRESETS["llama_tiny"]

PROMPTS = [[1, 2, 3, 4, 5], [6, 7, 8], [9, 10, 11, 12], [13, 14]]

GREEDY = SamplingParams(max_tokens=8, temperature=0.0)
SEEDED = SamplingParams(max_tokens=8, temperature=0.9, seed=7)


@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _ec(**over):
    base = dict(max_seqs=4, block_size=8, num_blocks=64, max_model_len=128,
                cache_dtype="float32", eos_token_id=-1)
    base.update(over)
    return EngineConfig(**base)


# ----------------------------------------------------------------------
# Thread-based fake spawner (the test seam make_subprocess_spawner names)
# ----------------------------------------------------------------------

class _ThreadHandle:
    """Process-handle protocol over an in-process EngineWorker thread.

    ``kill()`` closes the worker's listener AND its live supervisor
    connection, so the supervisor's next RPC fails exactly like it does
    against a SIGKILL'd process."""

    _pids = itertools.count(900000)

    def __init__(self, worker: EngineWorker):
        self.worker = worker
        self.pid = next(self._pids)
        self.thread = threading.Thread(target=worker.serve_forever,
                                       daemon=True)
        self.thread.start()

    def port(self):
        return self.worker.port

    def poll(self):
        return None if self.thread.is_alive() else 0

    def wait(self, timeout=None):
        self.thread.join(timeout)
        if self.thread.is_alive():
            raise TimeoutError("worker thread still serving")
        return 0

    def terminate(self):
        self.worker.close()

    kill = terminate


def _thread_spawner(params, **engine_over):
    """spawner(idx, generation) building a fresh engine per incarnation
    from the shared (NOT donated) param tree — every worker holds
    identical weights, like the subprocess PRNGKey(0) preset path."""
    spawned = []

    def spawn(idx: int, generation: int) -> _ThreadHandle:
        engine = InferenceEngine(CFG, params, _ec(**engine_over))
        handle = _ThreadHandle(EngineWorker(engine, port=0, worker_id=idx))
        spawned.append((idx, generation, handle))
        return handle

    spawn.spawned = spawned
    return spawn


def _fleet_cfg(**over):
    base = dict(workers=2, health_interval_s=0.05, respawn_backoff_s=0.05,
                respawn_backoff_max_s=0.5, startup_timeout_s=120.0,
                rpc_timeout_s=60.0, term_grace_s=2.0)
    base.update(over)
    return FleetConfig(**base)


def _make_fleet(params, *, workers=2, heal=True, engine_over=None,
                **sup_kwargs):
    spawner = _thread_spawner(params, **(engine_over or {}))
    lc = ReplicaLifecycleConfig(enabled=heal, probation_initial_s=0.05,
                                probation_max_s=0.5)
    return FleetSupervisor(
        _ec(**(engine_over or {})), workers=workers, spawner=spawner,
        fleet_cfg=_fleet_cfg(workers=workers), lifecycle_cfg=lc,
        canary_vocab=CFG.vocab_size, **sup_kwargs)


def _expected(params_tree, sp, **engine_over):
    eng = InferenceEngine(CFG, params_tree, _ec(**engine_over))
    return {tuple(p): (r.output_token_ids, r.output_logprobs)
            for p, r in zip(PROMPTS, eng.generate(PROMPTS, sp))}


# ----------------------------------------------------------------------
# Byte-identity: fleet == single-process engine
# ----------------------------------------------------------------------

@pytest.mark.parametrize("sp", [GREEDY, SEEDED], ids=["greedy", "seeded"])
def test_fleet_outputs_byte_identical_to_single_process(tiny_params, sp):
    expect = _expected(tiny_params, sp)
    sup = _make_fleet(tiny_params, workers=2)
    try:
        results = sup.generate(PROMPTS, sp)
        # Work genuinely spread across both workers.
        per_worker = [sup.fleet_scalars()[f"fleet_w{i}_requests"]
                      for i in range(2)]
        assert all(v > 0 for v in per_worker), per_worker
        for p, r in zip(PROMPTS, results):
            toks, lps = expect[tuple(p)]
            assert r.output_token_ids == toks
            assert [float(x) for x in r.output_logprobs] \
                == [float(x) for x in lps]
            assert r.finish_reason == "length"
    finally:
        sup.close()


@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
@pytest.mark.parametrize("sp", [GREEDY, SEEDED], ids=["greedy", "seeded"])
def test_fleet_migration_byte_identical(tiny_params, kv_dtype, sp):
    """Drain one worker mid-decode: its requests cross the process
    boundary as verbatim KV-handoff envelopes and still finish with
    EXACTLY the single-engine tokens — bf16 and int8 KV payloads."""
    expect = _expected(tiny_params, sp, cache_dtype=kv_dtype)
    sup = _make_fleet(tiny_params, workers=2,
                      engine_over={"cache_dtype": kv_dtype})
    try:
        reqs = [sup.submit(p, sp) for p in PROMPTS]
        for _ in range(60):
            sup.step()
            if all(len(r.output_token_ids) >= 2 for r in reqs):
                break
        assert all(not r.done for r in reqs)
        victim = next(w for w in sup._workers if w.owned)
        before = {r.request_id: list(r.output_token_ids) for r in reqs}
        errored = sup.drain_replica(victim.idx, kind="preempt",
                                    quarantine=False)
        assert errored == []
        while sup.has_work:
            sup.step()
        migrated = [r for r in reqs if r.num_migrations > 0]
        assert migrated, "drain must migrate at least one mid-decode request"
        for r in migrated:
            # Mid-flight tokens survived the envelope (mirror kept them).
            assert r.output_token_ids[:len(before[r.request_id])] \
                == before[r.request_id]
        for p, r in zip(PROMPTS, reqs):
            toks, _ = expect[tuple(p)]
            assert r.output_token_ids == toks, \
                f"{r.request_id} (migrations={r.num_migrations})"
            assert r.finish_reason == "length"
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Kill -> failover + respawn
# ----------------------------------------------------------------------

def test_fleet_kill_failover_respawn_zero_errors(tiny_params):
    respawns_before = fleet.respawns_total.value
    sup = _make_fleet(tiny_params, workers=2)
    try:
        sp = SamplingParams(max_tokens=12, temperature=0.0)
        reqs = [sup.submit(p, sp) for p in PROMPTS]
        for _ in range(60):
            sup.step()
            if any(r.output_token_ids for r in reqs):
                break
        victim = next(w for w in sup._workers if w.owned)
        scal_before = sup.fleet_scalars()
        victim.handle.kill()  # SIGKILL analog mid-decode
        deadline = time.monotonic() + 60
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
        # Zero client errors: every request finished normally on the
        # survivor (failover resubmits recompute from mirror tokens).
        assert [r.finish_reason for r in reqs] == ["length"] * len(reqs)
        assert sup.failover["replica_faults"] >= 1
        assert sup.failover["failover_errors"] == 0
        # The replacement process canaries back in.
        while sup._respawns < 1 and time.monotonic() < deadline:
            sup.step()
            time.sleep(0.005)
        assert sup._respawns >= 1
        assert fleet.respawns_total.value >= respawns_before + 1
        assert sup.worker_states()[str(victim.idx)] == "live"
        assert sup.num_live == 2
        # Federated per-worker counters stayed monotonic across the
        # respawn (stats_carry) and new work reaches the replacement.
        scal_after = sup.fleet_scalars()
        for k in fleet.WORKER_COUNTER_KEYS:
            key = f"fleet_w{victim.idx}_{k}"
            assert scal_after[key] >= scal_before[key], key
        assert scal_after["fleet_respawns"] >= 1
        r2 = sup.generate(PROMPTS[:2], GREEDY)
        assert all(r.finish_reason == "length" for r in r2)
    finally:
        sup.close()


def test_fleet_total_outage_queues_until_respawn(tiny_params):
    """Every worker dead at once: submits queue during the respawn window
    instead of erroring, then drain once a replacement is live."""
    sup = _make_fleet(tiny_params, workers=2)
    try:
        for w in list(sup._workers):
            w.handle.kill()
        deadline = time.monotonic() + 60
        while sup.num_live > 0 and time.monotonic() < deadline:
            sup.step()  # discover the deaths
        req = sup.submit(PROMPTS[0], GREEDY)  # _reviving() holds the queue
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
            time.sleep(0.005)
        assert req.finish_reason == "length"
        assert sup._respawns >= 1
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Robustness: worker survives garbage, supervisor survives evil peers
# ----------------------------------------------------------------------

def _connect(port):
    s = wire.connect_with_retry("127.0.0.1", port, timeout_s=10.0)
    s.settimeout(30.0)  # a hung reply should fail the test, not the suite
    return s


def test_worker_survives_malformed_frames(tiny_params):
    engine = InferenceEngine(CFG, tiny_params, _ec())
    worker = EngineWorker(engine, port=0, worker_id=3,
                          max_frame_bytes=1 << 20)
    t = threading.Thread(target=worker.serve_forever, daemon=True)
    t.start()
    try:
        # 1. Not the protocol at all (HTTP bytes): FT_ERROR or a drop,
        # never a worker death.
        s = _connect(worker.port)
        s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        try:
            ftype, payload = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireBadMagic" in wire.unpack_obj(payload)["error"]
        except wire.WireError:
            pass  # connection torn down before the reply landed: also fine
        s.close()

        # 2. Truncated mid-frame (peer death): worker drops and re-accepts.
        s = _connect(worker.port)
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_STEP, 512)[:7])
        s.close()

        # 3. Version from the future.
        s = _connect(worker.port)
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION + 7,
                                    wire.FT_STEP, 0))
        try:
            ftype, payload = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireVersionMismatch" in wire.unpack_obj(payload)["error"]
        except wire.WireError:
            pass
        s.close()

        # 4. Oversized declared payload: refused without allocation.
        s = _connect(worker.port)
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_ADOPT, (1 << 20) + 1))
        try:
            ftype, payload = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireFrameTooLarge" in wire.unpack_obj(payload)["error"]
        except wire.WireError:
            pass
        s.close()

        # 5. Digest corruption: caught before dispatch.
        s = _connect(worker.port)
        payload = wire.pack_obj({"request": {}})
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_ADOPT, len(payload))
                  + payload + b"\x00" * wire._DIGEST_BYTES)
        try:
            ftype, reply = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireDigestMismatch" in wire.unpack_obj(reply)["error"]
        except wire.WireError:
            pass
        s.close()

        # 6. Well-formed frame of an unexpected type: FT_ERROR reply and
        # the SAME connection keeps serving.
        s = _connect(worker.port)
        with pytest.raises(wire.WireRemoteError, match="unexpected frame"):
            wire.request_reply(s, wire.FT_STEP_RESULT, {})
        reply = wire.request_reply(s, wire.FT_HEALTH, {})
        assert reply["ok"] and reply["worker_id"] == 3

        # 7. And the engine still actually works.
        r = wire.request_reply(s, wire.FT_SUBMIT, {
            "request": wire.request_to_wire(Request(
                request_id="post-garbage", prompt_token_ids=[1, 2, 3],
                params=SamplingParams(max_tokens=2, temperature=0.0),
                arrival_time=time.monotonic())),
            "resubmit": False})
        assert r["ok"]
        for _ in range(50):
            reply = wire.request_reply(s, wire.FT_STEP, {"cancels": []})
            done = [ev for ev in reply["events"]
                    if ev["id"] == "post-garbage"
                    and "finish_reason" in ev]
            if done:
                assert done[0]["finish_reason"] == "length"
                break
        else:
            pytest.fail("request did not finish after garbage storm")
        s.close()
    finally:
        worker.close()
        t.join(timeout=10)
        assert not t.is_alive(), "worker thread must exit on close()"


class _EvilHandle:
    """A 'worker' that handshakes health correctly, then answers every
    other frame with a digest-corrupted reply."""

    def __init__(self):
        self.pid = 66666
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(2)
        self._port = self._listener.getsockname()[1]
        self._stop = False
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                while not self._stop:
                    ftype, _ = wire.recv_frame(conn)
                    if ftype == wire.FT_HEALTH:
                        wire.send_frame(conn, wire.FT_OK, wire.pack_obj(
                            {"ok": True, "pid": self.pid, "worker_id": 0,
                             "time": 0.0, "stats": {}, "metrics": {},
                             "active": 0, "waiting": 0, "free_blocks": 64,
                             "has_work": False}))
                        continue
                    payload = wire.pack_obj({"ok": True})
                    conn.sendall(wire._HEADER.pack(
                        wire.MAGIC, wire.WIRE_VERSION, wire.FT_OK,
                        len(payload)) + payload
                        + b"\xde" * wire._DIGEST_BYTES)
            except (wire.WireError, OSError):
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def port(self):
        return self._port

    def poll(self):
        return None if not self._stop else 0

    def wait(self, timeout=None):
        self.thread.join(timeout)
        return 0

    def terminate(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass

    kill = terminate


def test_supervisor_evicts_corrupt_peer_and_rehomes(tiny_params):
    """Worker 0 answers with digest-corrupted frames: the supervisor must
    evict it (never adopt the corrupt bytes, never hang) and finish the
    request on the healthy worker."""
    good = _thread_spawner(tiny_params)

    def spawn(idx, generation):
        if idx == 0:
            return _EvilHandle()
        return good(idx, generation)

    sup = FleetSupervisor(
        _ec(), workers=2, spawner=spawn, fleet_cfg=_fleet_cfg(),
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=False),
        canary_vocab=CFG.vocab_size)
    try:
        req = sup.submit(PROMPTS[0], GREEDY)
        deadline = time.monotonic() + 60
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
        assert req.finish_reason == "length", \
            "request must finish on the healthy worker"
        assert req.replica == 1
        assert sup.failover["replica_faults"] >= 1
        assert sup.worker_states()["0"] == "dead"  # healing off: stays dead
        assert sup.num_live == 1
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Facade surface + federation arithmetic
# ----------------------------------------------------------------------

def test_fleet_facade_and_federation(tiny_params):
    sup = _make_fleet(tiny_params, workers=2)
    try:
        sup.generate(PROMPTS, GREEDY)
        scal = sup.fleet_scalars()
        stats = sup.stats
        # Per-worker federated counters sum exactly to the fleet totals —
        # the equality loadgen's federation check asserts over /metrics.
        for k in fleet.WORKER_COUNTER_KEYS:
            worker_sum = sum(scal[f"fleet_w{i}_{k}"] for i in range(2))
            assert worker_sum == stats.get(k, 0), k
        assert scal["fleet_workers"] == 2.0
        assert scal["fleet_workers_live"] == 2.0
        assert scal["fleet_w0_up"] == 1.0 and scal["fleet_w1_up"] == 1.0
        for key in sup.fleet_gauge_keys:
            assert key in scal, key
        assert len(stats["replicas"]) == 2
        assert sup.lifecycle_counts()["live"] == 2
        assert set(sup.worker_states().values()) == {"live"}
        assert sup.respawn_retry_after_s == 0.0
        assert sup.cfg.max_seqs == 4
        assert fleet.workers_alive_gauge.value == 2.0

        # Loadgen's hardcoded key mirror must track the fleet contract.
        from dlti_tpu.benchmarks import loadgen

        assert loadgen._FLEET_COUNTER_KEYS == fleet.WORKER_COUNTER_KEYS

        # abort_all finishes every mirror and clears the pending queue.
        reqs = [sup.submit(p, SamplingParams(max_tokens=64))
                for p in PROMPTS]
        sup.step()
        aborted = sup.abort_all(reason="abort")
        assert {r.request_id for r in aborted} \
            == {r.request_id for r in reqs}
        assert all(r.finish_reason == "abort" for r in reqs)
        assert not sup.has_work
        assert sup.num_active == 0
    finally:
        sup.close()


def test_fleet_sticky_affinity_and_cancel(tiny_params):
    sup = _make_fleet(tiny_params, workers=2)
    try:
        # Same affinity key -> same worker (rendezvous hash), booked as
        # sticky routes.
        r1 = sup.submit(PROMPTS[0], GREEDY, affinity_key="session-A")
        sup.step()
        r2 = sup.submit(PROMPTS[1], GREEDY, affinity_key="session-A")
        sup.step()
        assert r1.replica == r2.replica
        assert sup.affinity["sticky"] >= 2
        # Cancellation propagates over the wire as a step piggyback.
        r3 = sup.submit(PROMPTS[2], SamplingParams(max_tokens=64))
        sup.step()
        r3.cancel_requested = True
        deadline = time.monotonic() + 30
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
        # Server-side cancel finishes as a normal "stop", long before
        # max_tokens would.
        assert r3.finish_reason == "stop"
        assert len(r3.output_token_ids) < 64
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Distributed tracing: span federation + per-request timelines
# ----------------------------------------------------------------------

def _traced_thread_spawner(params, **engine_over):
    """Thread spawner whose workers carry PRIVATE per-worker tracers.

    In-process fake workers would otherwise share the process-global
    tracer with the supervisor — every span would be both local AND
    "shipped", hiding federation bugs. A private ring per incarnation
    mirrors what a real worker process has."""
    from dlti_tpu.telemetry import RequestTelemetry, SpanTracer

    def spawn(idx: int, generation: int) -> _ThreadHandle:
        wtracer = SpanTracer(capacity=4096, enabled=True)
        wtracer.process_label = f"worker{idx} gen{generation}"
        telemetry = RequestTelemetry(tracer=wtracer)

        def build(tree):
            return InferenceEngine(CFG, tree, _ec(**engine_over),
                                   telemetry=telemetry)

        return _ThreadHandle(EngineWorker(build(params), port=0,
                                          worker_id=idx, tracer=wtracer,
                                          reload_fn=build))

    return spawn


@contextlib.contextmanager
def _global_tracer(label="supervisor"):
    """Enable the process-global tracer (supervisor/gateway spans) for
    one test, restoring its prior state after."""
    from dlti_tpu.telemetry import get_tracer

    t = get_tracer()
    prev = (t.enabled, t.process_label)
    t.enabled = True
    t.process_label = label
    try:
        yield t
    finally:
        t.enabled, t.process_label = prev


def test_fleet_trace_context_survives_migration_and_failover(tiny_params):
    """The trace_id minted at submit rides the FT_SUBMIT, the drain
    migration envelope, AND the kill-failover resubmit unchanged; worker
    span tails federate back with clock offsets and join the supervisor's
    local spans into one multi-process timeline."""
    from dlti_tpu.telemetry import get_tracer
    from dlti_tpu.telemetry.distributed_trace import (
        TraceFederator, request_timeline,
    )

    # 3 workers: after the drain takes the victim out of rotation, the
    # kill still leaves a live survivor for the failover resubmits.
    sup = FleetSupervisor(
        _ec(), workers=3, spawner=_traced_thread_spawner(tiny_params),
        fleet_cfg=_fleet_cfg(workers=3),
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=False),
        canary_vocab=CFG.vocab_size)
    with _global_tracer():
        try:
            sp = SamplingParams(max_tokens=12, temperature=0.0)
            reqs = [sup.submit(p, sp) for p in PROMPTS]
            ids = {r.request_id: r.trace_id for r in reqs}
            assert all(len(t) == 16 for t in ids.values())
            assert len(set(ids.values())) == len(ids), "trace ids collide"
            for _ in range(60):
                sup.step()
                if all(len(r.output_token_ids) >= 2 for r in reqs):
                    break
            assert all(not r.done for r in reqs)
            # Leg 1: drain -> cross-process KV migration.
            victim = next(w for w in sup._workers if w.owned)
            assert sup.drain_replica(victim.idx, kind="preempt",
                                     quarantine=False) == []
            assert {r.request_id: r.trace_id for r in reqs} == ids
            # Leg 2: SIGKILL-analog on one new owner -> failover resubmit.
            next(w for w in sup._workers if w.owned).handle.kill()
            deadline = time.monotonic() + 60
            while sup.has_work and time.monotonic() < deadline:
                sup.step()
            assert [r.finish_reason for r in reqs] == ["length"] * len(reqs)
            assert {r.request_id: r.trace_id for r in reqs} == ids
            # Federation: multiple workers shipped spans; every worker's
            # clock got an offset estimate with a real uncertainty bound.
            fed = sup.trace
            assert len(fed) > 0
            pids = {ev["pid"] for ev in fed.events()}
            assert len(pids) >= 2, pids
            assert all(p >= TraceFederator.SYNTHETIC_PID_BASE
                       for p in pids), pids
            offs = fed.offsets()
            assert set(offs) == {"0", "1", "2"}
            for o in offs.values():
                assert o["uncertainty_s"] is not None
                assert o["uncertainty_s"] >= 0.0
            # A migrated request reconstructs as ONE timeline spanning
            # the supervisor + >=2 worker processes, with the handoff leg.
            migrated = next(r for r in reqs if r.num_migrations > 0)
            events = fed.events() + get_tracer().events()
            tl = request_timeline(events, migrated.request_id)
            assert tl["trace_id"] == migrated.trace_id
            assert len(tl["processes"]) >= 2, tl["processes"]
            assert "engine/kv_handoff" in tl["legs"]
            assert {"request/prefill", "request/decode"} <= set(tl["legs"])
            ts = [ev["ts"] for ev in tl["spans"]]
            assert ts == sorted(ts), "spans must be causally ordered"
            # The handoff overlaps the lifecycle legs: reported, but the
            # sequential union never double-counts it.
            assert "engine/kv_handoff" not in tl["sequential_legs"]
        finally:
            sup.close()


def _trace_drill(sup, params):
    """The cross-process acceptance drill body, shared by the fast
    thread-fleet tier and the slow real-subprocess tier: serve the fleet
    behind a gateway'd HTTP server, run loadgen while a chaos thread
    triggers one rolling reload mid-run (drain-via-migration on the
    stepper thread), then reconstruct a migrated request's timeline via
    GET /debug/trace?request_id=. Returns (report, record, timeline,
    merged_trace_dict)."""
    from dlti_tpu.benchmarks import LoadGenConfig, run_load_test
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.serving.server import ServerConfig, make_server

    httpd = None
    try:
        httpd, async_engine = make_server(
            sup, IdTokenizer(vocab_size=CFG.vocab_size),
            ServerConfig(host="127.0.0.1", port=0,
                         default_params=SamplingParams(max_tokens=8),
                         gateway=GatewayConfig(enabled=True)))
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()

        reloaded = threading.Event()

        def chaos():
            # As soon as a worker holds live work, queue a rolling
            # reload (same weights): the stepper thread drains each
            # worker via KV migration — the chaos-triggered
            # cross-process handoff, byte-identical outputs.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if any(w.owned for w in sup._workers):
                    sup.request_reload(lambda: params)
                    reloaded.set()
                    return
                time.sleep(0.01)

        chaos_t = threading.Thread(target=chaos, daemon=True)
        chaos_t.start()
        report = run_load_test(LoadGenConfig(
            host="127.0.0.1", port=port, num_requests=24, concurrency=4,
            max_tokens=8, stream=True, prompt="trace", timeout_s=300,
            scrape_debug_vars=True))
        chaos_t.join(timeout=60)
        assert reloaded.is_set(), "no worker was ever holding work"
        deadline = time.monotonic() + 120
        while sup._reload is not None and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sup._reload is None, "rolling reload never completed"

        assert report.num_ok == report.num_requests, report.errors
        assert report.errors == []
        migrated = [r for r in report.records
                    if r.ok and r.migrations > 0 and r.request_id]
        assert migrated, "chaos reload must migrate >=1 live request"
        rec = max(migrated, key=lambda r: r.latency)
        assert rec.trace_id, "stream must surface the trace id"

        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        try:
            conn.request("GET", f"/debug/trace?request_id={rec.request_id}"
                                f"&latency_s={rec.latency}")
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()
            tl = json.loads(resp.read())
            conn.request("GET", "/debug/trace")
            resp = conn.getresponse()
            assert resp.status == 200
            merged = json.loads(resp.read())
        finally:
            conn.close()
        return report, rec, tl, merged
    finally:
        if httpd is not None:
            httpd.shutdown()
            async_engine.shutdown()
            httpd.server_close()
        sup.close()


def _assert_drill_timeline(report, rec, tl, merged):
    """The ISSUE acceptance assertions over the drill artifacts."""
    # Coverage: nearly every sampled ok request reconstructs with its
    # gateway + prefill + decode legs present end-of-run.
    assert report.trace_coverage > 0.9, report.trace_coverage
    # One clock-aligned timeline with spans from >= 2 processes and
    # every acceptance leg, causally ordered.
    assert tl["trace_id"] == rec.trace_id
    assert len(tl["processes"]) >= 2, tl["processes"]
    assert {"gateway/queued", "request/prefill", "request/decode",
            "engine/kv_handoff"} <= set(tl["legs"]), sorted(tl["legs"])
    ts = [ev["ts"] for ev in tl["spans"]]
    assert ts == sorted(ts), "spans must be causally ordered"
    # Per-leg coverage within 5% of the client-observed latency (tiny
    # absolute floor: sub-100ms requests bottom out at HTTP overhead).
    assert tl["client_latency_s"] == pytest.approx(rec.latency)
    assert abs(tl["residual_s"]) <= max(0.05 * rec.latency, 0.005), tl
    # The merged snapshot is a multi-process Perfetto timeline: one
    # process_name row per source (supervisor + both workers) and a
    # clock-offset table covering both workers.
    metas = [ev for ev in merged["traceEvents"] if ev.get("ph") == "M"]
    assert len(metas) >= 3, metas
    assert {"0", "1"} <= set(merged["clockOffsets"])


def test_fleet_distributed_trace_cross_process_drill(tiny_params):
    """Fast tier of the acceptance drill: thread-spawner fleet with
    private per-worker tracers, gateway'd server, live loadgen, one
    chaos-triggered migration, zero client errors, and a single
    clock-aligned per-request timeline via /debug/trace."""
    sup = FleetSupervisor(
        _ec(), workers=2, spawner=_traced_thread_spawner(tiny_params),
        fleet_cfg=_fleet_cfg(),
        lifecycle_cfg=ReplicaLifecycleConfig(
            enabled=True, probation_initial_s=0.05, probation_max_s=0.5),
        canary_vocab=CFG.vocab_size)
    with _global_tracer():
        report, rec, tl, merged = _trace_drill(sup, tiny_params)
    _assert_drill_timeline(report, rec, tl, merged)
    assert report.migrations_total >= 1


# ----------------------------------------------------------------------
# Subprocess drills (slow tier): the real engine_worker.py processes
# ----------------------------------------------------------------------

def _subprocess_spec(**engine_over):
    return {
        "model_preset": "llama_tiny",
        "engine": dataclasses.asdict(_ec(**engine_over)),
        # conftest forces true-fp32 matmuls in THIS process; workers need
        # the same knob for cross-process byte identity.
        "matmul_precision": "highest",
        "warmup": False,  # lazy compiles keep the drill's boot short
    }


def _mk_subprocess_fleet(tmp_path, *, workers=2, heal=True, flight_dir=None,
                         **engine_over):
    spec = _subprocess_spec(**engine_over)
    if flight_dir:
        spec["flight_dir"] = flight_dir
    spawner = make_subprocess_spawner(spec, str(tmp_path))
    return FleetSupervisor(
        _ec(**engine_over), workers=workers, spawner=spawner,
        fleet_cfg=_fleet_cfg(workers=workers, startup_timeout_s=600.0,
                             respawn_backoff_s=0.2),
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=heal,
                                             probation_initial_s=0.2),
        canary_vocab=CFG.vocab_size)


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
@pytest.mark.parametrize("sp", [GREEDY, SEEDED], ids=["greedy", "seeded"])
def test_subprocess_fleet_byte_identical_with_migration(
        tmp_path, tiny_params, kv_dtype, sp):
    """The acceptance drill: --fleet-workers 2 (real processes) produces
    byte-identical outputs to --replicas 2 (in-process), greedy and
    seeded, bf16 and int8 KV — including one cross-process migration."""
    ref = ReplicatedEngine(CFG, tiny_params, _ec(cache_dtype=kv_dtype),
                           replicas=2)
    expect = {tuple(p): r.output_token_ids
              for p, r in zip(PROMPTS, ref.generate(PROMPTS, sp))}

    sup = _mk_subprocess_fleet(tmp_path, workers=2, cache_dtype=kv_dtype)
    try:
        reqs = [sup.submit(p, sp) for p in PROMPTS]
        for _ in range(120):
            sup.step()
            if all(len(r.output_token_ids) >= 2 for r in reqs):
                break
        assert all(not r.done for r in reqs)
        victim = next(w for w in sup._workers if w.owned)
        errored = sup.drain_replica(victim.idx, kind="preempt",
                                    quarantine=False)
        assert errored == []
        while sup.has_work:
            sup.step()
        assert any(r.num_migrations > 0 for r in reqs)
        for p, r in zip(PROMPTS, reqs):
            assert r.output_token_ids == expect[tuple(p)], \
                f"{r.request_id} (migrations={r.num_migrations})"
            assert r.finish_reason == "length"
    finally:
        sup.close()


@pytest.mark.slow
def test_subprocess_fleet_chaos_sigkill_under_load(tmp_path):
    """Live loadgen against serve-over-fleet; SIGKILL one worker process
    mid-run. Demands: zero client errors, dlti_fleet_respawns_total >= 1,
    and federated per-worker /metrics series that sum to the fleet
    totals (LoadReport.fleet_federation)."""
    from dlti_tpu.benchmarks import LoadGenConfig, run_load_test
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.serving.server import ServerConfig, make_server

    from dlti_tpu.telemetry.flightrecorder import FlightRecorder, install

    flight_dir = str(tmp_path / "flight")
    # Supervisor-side recorder: _fail_worker dumps the fault at the dump
    # root; the worker processes dump under worker{N}/ (spec flight_dir).
    prev_recorder = install(FlightRecorder(flight_dir))
    sup = _mk_subprocess_fleet(tmp_path, workers=2, flight_dir=flight_dir)
    httpd = None
    # The supervisor-side dump carries only its own span tail (a
    # SIGKILL'd worker never gets to dump), so the merge below needs the
    # process-global tracer recording.
    stack = contextlib.ExitStack()
    stack.enter_context(_global_tracer())
    try:
        httpd, async_engine = make_server(
            sup, IdTokenizer(vocab_size=CFG.vocab_size),
            ServerConfig(host="127.0.0.1", port=0,
                         default_params=SamplingParams(max_tokens=8)))
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()

        kill_done = threading.Event()

        def assassin():
            # Let traffic build, then SIGKILL a live worker mid-decode.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                victims = [w for w in sup._workers
                           if w.pid and w.sock is not None and w.owned]
                if victims:
                    os.kill(victims[0].pid, signal.SIGKILL)
                    kill_done.set()
                    return
                time.sleep(0.05)

        killer = threading.Thread(target=assassin, daemon=True)
        killer.start()
        report = run_load_test(LoadGenConfig(
            host="127.0.0.1", port=port, num_requests=24, concurrency=4,
            max_tokens=8, stream=True, prompt="chaos", timeout_s=300,
            scrape_debug_vars=True))
        killer.join(timeout=60)
        assert kill_done.is_set(), "no worker was ever holding work"

        # Zero client errors through the kill + respawn.
        assert report.num_ok == report.num_requests, report.errors
        assert report.errors == []

        # The killed worker respawned.
        deadline = time.monotonic() + 120
        while sup._respawns < 1 and time.monotonic() < deadline:
            time.sleep(0.2)
        assert sup._respawns >= 1
        assert fleet.respawns_total.value >= 1

        # Federation: per-worker series were scraped and sum to totals.
        fed = report.fleet_federation
        assert fed, "fleet federation block missing from LoadReport"
        assert sorted(fed["workers"]) == [0, 1]
        assert fed["consistent"], fed["checks"]
        # respawns_total increments at REINSTATE time (boot + canary),
        # which can land after the load finishes — the report's scrape
        # may legitimately predate it. Re-scrape now that the respawn
        # wait above has completed.
        from dlti_tpu.benchmarks.loadgen import _fleet_federation_report
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
        conn.request("GET", "/metrics")
        raw = conn.getresponse().read()
        conn.close()
        fed_now = _fleet_federation_report(raw.decode(errors="replace"))
        assert fed_now["respawns_total"] >= 1

        # Satellite: postmortem --all merges the per-worker dump tree
        # (the SIGKILL'd worker's supervisor-side dump is at the root).
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "scripts"))
        try:
            import postmortem
            dumps = postmortem.discover_dumps(flight_dir)
            merged = postmortem.merge_incident_trace(dumps)
        finally:
            sys.path.pop(0)
        assert dumps, "worker fault must leave a flight dump"
        # The dumps' span tails merge onto one clock (offsets persisted
        # in each dump's context.json; supervisor dumps rebase at 0).
        assert merged is not None, "dump span tails must merge"
        assert merged["traceEvents"]
        assert merged["sources"]
    finally:
        stack.close()
        install(prev_recorder)
        if httpd is not None:
            httpd.shutdown()
            async_engine.shutdown()
            httpd.server_close()
        sup.close()


@pytest.mark.slow
def test_subprocess_fleet_distributed_trace_drill(tmp_path, tiny_params):
    """The real acceptance drill: 2 engine_worker.py PROCESSES (each with
    its own monotonic clock and process-global tracer), gateway'd server,
    loadgen with one chaos-triggered rolling-reload migration, zero
    client errors — and a sampled migrated request whose /debug/trace
    timeline is clock-aligned across genuinely distinct processes with
    the per-leg sum within 5% of the client-observed latency."""
    sup = _mk_subprocess_fleet(tmp_path, workers=2)
    with _global_tracer():
        report, rec, tl, merged = _trace_drill(sup, tiny_params)
    _assert_drill_timeline(report, rec, tl, merged)
    assert report.migrations_total >= 1
    # Real processes: the worker span pids are the federator's synthetic
    # render pids (stable rows), while the process_name metadata carries
    # the real pids the supervisor observed at health time.
    from dlti_tpu.telemetry.distributed_trace import TraceFederator

    worker_pids = [p for p in tl["processes"]
                   if p >= TraceFederator.SYNTHETIC_PID_BASE]
    assert worker_pids, tl["processes"]
    metas = [ev for ev in merged["traceEvents"] if ev.get("ph") == "M"
             and ev.get("pid", 0) >= TraceFederator.SYNTHETIC_PID_BASE]
    assert any("pid" in (ev.get("args") or {}).get("name", "")
               for ev in metas), metas
