"""Multi-process serving fleet tests (dlti_tpu.serving.fleet).

Layers:

* **Thread-spawner fast tier** — the spawner seam injects in-process
  ``EngineWorker`` threads instead of real processes, so the full
  supervisor ↔ worker wire conversation (submit / step / drain / adopt /
  health / abort) runs in seconds:
  - byte-identity with a single-process engine (greedy and seeded),
  - cross-worker KV-handoff migration on drain, byte-identical, bf16 and
    int8 KV (the envelope's numpy payloads round-trip byte-exactly),
  - kill → failover + canary-gated respawn with zero client errors and
    monotonic per-worker counters,
  - a worker that survives garbage/truncated/oversized/corrupt frames
    and still answers a clean health round-trip,
  - an evil peer speaking corrupt frames: the supervisor evicts it and
    rehomes its work instead of hanging or corrupting an adoption,
  - the ReplicatedEngine-compatible facade + federation arithmetic
    (per-worker counter sums == fleet totals; loadgen's key mirror).
* **Subprocess slow tier** — the real ``scripts/engine_worker.py``
  drill: ``--fleet-workers 2`` outputs byte-identical to an in-process
  2-replica engine (greedy + seeded, incl. one cross-process migration),
  and a live-loadgen chaos drill that SIGKILLs a worker mid-run and
  demands zero client errors, a respawn, and consistent federated
  metrics.
"""

import dataclasses
import itertools
import os
import signal
import socket
import threading
import time

import jax
import jax.numpy as jnp
import pytest

from dlti_tpu.config import (
    FleetConfig, MODEL_PRESETS, ReplicaLifecycleConfig,
)
from dlti_tpu.models import LlamaForCausalLM
from dlti_tpu.serving import (
    EngineConfig, InferenceEngine, ReplicatedEngine, SamplingParams,
)
from dlti_tpu.serving import fleet, wire
from dlti_tpu.serving.engine import Request
from dlti_tpu.serving.fleet import FleetSupervisor, make_subprocess_spawner
from dlti_tpu.serving.worker import EngineWorker

CFG = MODEL_PRESETS["llama_tiny"]

PROMPTS = [[1, 2, 3, 4, 5], [6, 7, 8], [9, 10, 11, 12], [13, 14]]

GREEDY = SamplingParams(max_tokens=8, temperature=0.0)
SEEDED = SamplingParams(max_tokens=8, temperature=0.9, seed=7)


@pytest.fixture(scope="module")
def tiny_params():
    model = LlamaForCausalLM(CFG, None)
    return model.init(jax.random.PRNGKey(0),
                      jnp.zeros((1, 8), jnp.int32))["params"]


def _ec(**over):
    base = dict(max_seqs=4, block_size=8, num_blocks=64, max_model_len=128,
                cache_dtype="float32", eos_token_id=-1)
    base.update(over)
    return EngineConfig(**base)


# ----------------------------------------------------------------------
# Thread-based fake spawner (the test seam make_subprocess_spawner names)
# ----------------------------------------------------------------------

class _ThreadHandle:
    """Process-handle protocol over an in-process EngineWorker thread.

    ``kill()`` closes the worker's listener AND its live supervisor
    connection, so the supervisor's next RPC fails exactly like it does
    against a SIGKILL'd process."""

    _pids = itertools.count(900000)

    def __init__(self, worker: EngineWorker):
        self.worker = worker
        self.pid = next(self._pids)
        self.thread = threading.Thread(target=worker.serve_forever,
                                       daemon=True)
        self.thread.start()

    def port(self):
        return self.worker.port

    def poll(self):
        return None if self.thread.is_alive() else 0

    def wait(self, timeout=None):
        self.thread.join(timeout)
        if self.thread.is_alive():
            raise TimeoutError("worker thread still serving")
        return 0

    def terminate(self):
        self.worker.close()

    kill = terminate


def _thread_spawner(params, **engine_over):
    """spawner(idx, generation) building a fresh engine per incarnation
    from the shared (NOT donated) param tree — every worker holds
    identical weights, like the subprocess PRNGKey(0) preset path."""
    spawned = []

    def spawn(idx: int, generation: int) -> _ThreadHandle:
        engine = InferenceEngine(CFG, params, _ec(**engine_over))
        handle = _ThreadHandle(EngineWorker(engine, port=0, worker_id=idx))
        spawned.append((idx, generation, handle))
        return handle

    spawn.spawned = spawned
    return spawn


def _fleet_cfg(**over):
    base = dict(workers=2, health_interval_s=0.05, respawn_backoff_s=0.05,
                respawn_backoff_max_s=0.5, startup_timeout_s=120.0,
                rpc_timeout_s=60.0, term_grace_s=2.0)
    base.update(over)
    return FleetConfig(**base)


def _make_fleet(params, *, workers=2, heal=True, engine_over=None,
                **sup_kwargs):
    spawner = _thread_spawner(params, **(engine_over or {}))
    lc = ReplicaLifecycleConfig(enabled=heal, probation_initial_s=0.05,
                                probation_max_s=0.5)
    return FleetSupervisor(
        _ec(**(engine_over or {})), workers=workers, spawner=spawner,
        fleet_cfg=_fleet_cfg(workers=workers), lifecycle_cfg=lc,
        canary_vocab=CFG.vocab_size, **sup_kwargs)


def _expected(params_tree, sp, **engine_over):
    eng = InferenceEngine(CFG, params_tree, _ec(**engine_over))
    return {tuple(p): (r.output_token_ids, r.output_logprobs)
            for p, r in zip(PROMPTS, eng.generate(PROMPTS, sp))}


# ----------------------------------------------------------------------
# Byte-identity: fleet == single-process engine
# ----------------------------------------------------------------------

@pytest.mark.parametrize("sp", [GREEDY, SEEDED], ids=["greedy", "seeded"])
def test_fleet_outputs_byte_identical_to_single_process(tiny_params, sp):
    expect = _expected(tiny_params, sp)
    sup = _make_fleet(tiny_params, workers=2)
    try:
        results = sup.generate(PROMPTS, sp)
        # Work genuinely spread across both workers.
        per_worker = [sup.fleet_scalars()[f"fleet_w{i}_requests"]
                      for i in range(2)]
        assert all(v > 0 for v in per_worker), per_worker
        for p, r in zip(PROMPTS, results):
            toks, lps = expect[tuple(p)]
            assert r.output_token_ids == toks
            assert [float(x) for x in r.output_logprobs] \
                == [float(x) for x in lps]
            assert r.finish_reason == "length"
    finally:
        sup.close()


@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
@pytest.mark.parametrize("sp", [GREEDY, SEEDED], ids=["greedy", "seeded"])
def test_fleet_migration_byte_identical(tiny_params, kv_dtype, sp):
    """Drain one worker mid-decode: its requests cross the process
    boundary as verbatim KV-handoff envelopes and still finish with
    EXACTLY the single-engine tokens — bf16 and int8 KV payloads."""
    expect = _expected(tiny_params, sp, cache_dtype=kv_dtype)
    sup = _make_fleet(tiny_params, workers=2,
                      engine_over={"cache_dtype": kv_dtype})
    try:
        reqs = [sup.submit(p, sp) for p in PROMPTS]
        for _ in range(60):
            sup.step()
            if all(len(r.output_token_ids) >= 2 for r in reqs):
                break
        assert all(not r.done for r in reqs)
        victim = next(w for w in sup._workers if w.owned)
        before = {r.request_id: list(r.output_token_ids) for r in reqs}
        errored = sup.drain_replica(victim.idx, kind="preempt",
                                    quarantine=False)
        assert errored == []
        while sup.has_work:
            sup.step()
        migrated = [r for r in reqs if r.num_migrations > 0]
        assert migrated, "drain must migrate at least one mid-decode request"
        for r in migrated:
            # Mid-flight tokens survived the envelope (mirror kept them).
            assert r.output_token_ids[:len(before[r.request_id])] \
                == before[r.request_id]
        for p, r in zip(PROMPTS, reqs):
            toks, _ = expect[tuple(p)]
            assert r.output_token_ids == toks, \
                f"{r.request_id} (migrations={r.num_migrations})"
            assert r.finish_reason == "length"
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Kill -> failover + respawn
# ----------------------------------------------------------------------

def test_fleet_kill_failover_respawn_zero_errors(tiny_params):
    respawns_before = fleet.respawns_total.value
    sup = _make_fleet(tiny_params, workers=2)
    try:
        sp = SamplingParams(max_tokens=12, temperature=0.0)
        reqs = [sup.submit(p, sp) for p in PROMPTS]
        for _ in range(60):
            sup.step()
            if any(r.output_token_ids for r in reqs):
                break
        victim = next(w for w in sup._workers if w.owned)
        scal_before = sup.fleet_scalars()
        victim.handle.kill()  # SIGKILL analog mid-decode
        deadline = time.monotonic() + 60
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
        # Zero client errors: every request finished normally on the
        # survivor (failover resubmits recompute from mirror tokens).
        assert [r.finish_reason for r in reqs] == ["length"] * len(reqs)
        assert sup.failover["replica_faults"] >= 1
        assert sup.failover["failover_errors"] == 0
        # The replacement process canaries back in.
        while sup._respawns < 1 and time.monotonic() < deadline:
            sup.step()
            time.sleep(0.005)
        assert sup._respawns >= 1
        assert fleet.respawns_total.value >= respawns_before + 1
        assert sup.worker_states()[str(victim.idx)] == "live"
        assert sup.num_live == 2
        # Federated per-worker counters stayed monotonic across the
        # respawn (stats_carry) and new work reaches the replacement.
        scal_after = sup.fleet_scalars()
        for k in fleet.WORKER_COUNTER_KEYS:
            key = f"fleet_w{victim.idx}_{k}"
            assert scal_after[key] >= scal_before[key], key
        assert scal_after["fleet_respawns"] >= 1
        r2 = sup.generate(PROMPTS[:2], GREEDY)
        assert all(r.finish_reason == "length" for r in r2)
    finally:
        sup.close()


def test_fleet_total_outage_queues_until_respawn(tiny_params):
    """Every worker dead at once: submits queue during the respawn window
    instead of erroring, then drain once a replacement is live."""
    sup = _make_fleet(tiny_params, workers=2)
    try:
        for w in list(sup._workers):
            w.handle.kill()
        deadline = time.monotonic() + 60
        while sup.num_live > 0 and time.monotonic() < deadline:
            sup.step()  # discover the deaths
        req = sup.submit(PROMPTS[0], GREEDY)  # _reviving() holds the queue
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
            time.sleep(0.005)
        assert req.finish_reason == "length"
        assert sup._respawns >= 1
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Robustness: worker survives garbage, supervisor survives evil peers
# ----------------------------------------------------------------------

def _connect(port):
    s = wire.connect_with_retry("127.0.0.1", port, timeout_s=10.0)
    s.settimeout(30.0)  # a hung reply should fail the test, not the suite
    return s


def test_worker_survives_malformed_frames(tiny_params):
    engine = InferenceEngine(CFG, tiny_params, _ec())
    worker = EngineWorker(engine, port=0, worker_id=3,
                          max_frame_bytes=1 << 20)
    t = threading.Thread(target=worker.serve_forever, daemon=True)
    t.start()
    try:
        # 1. Not the protocol at all (HTTP bytes): FT_ERROR or a drop,
        # never a worker death.
        s = _connect(worker.port)
        s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        try:
            ftype, payload = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireBadMagic" in wire.unpack_obj(payload)["error"]
        except wire.WireError:
            pass  # connection torn down before the reply landed: also fine
        s.close()

        # 2. Truncated mid-frame (peer death): worker drops and re-accepts.
        s = _connect(worker.port)
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_STEP, 512)[:7])
        s.close()

        # 3. Version from the future.
        s = _connect(worker.port)
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION + 7,
                                    wire.FT_STEP, 0))
        try:
            ftype, payload = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireVersionMismatch" in wire.unpack_obj(payload)["error"]
        except wire.WireError:
            pass
        s.close()

        # 4. Oversized declared payload: refused without allocation.
        s = _connect(worker.port)
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_ADOPT, (1 << 20) + 1))
        try:
            ftype, payload = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireFrameTooLarge" in wire.unpack_obj(payload)["error"]
        except wire.WireError:
            pass
        s.close()

        # 5. Digest corruption: caught before dispatch.
        s = _connect(worker.port)
        payload = wire.pack_obj({"request": {}})
        s.sendall(wire._HEADER.pack(wire.MAGIC, wire.WIRE_VERSION,
                                    wire.FT_ADOPT, len(payload))
                  + payload + b"\x00" * wire._DIGEST_BYTES)
        try:
            ftype, reply = wire.recv_frame(s)
            assert ftype == wire.FT_ERROR
            assert "WireDigestMismatch" in wire.unpack_obj(reply)["error"]
        except wire.WireError:
            pass
        s.close()

        # 6. Well-formed frame of an unexpected type: FT_ERROR reply and
        # the SAME connection keeps serving.
        s = _connect(worker.port)
        with pytest.raises(wire.WireRemoteError, match="unexpected frame"):
            wire.request_reply(s, wire.FT_STEP_RESULT, {})
        reply = wire.request_reply(s, wire.FT_HEALTH, {})
        assert reply["ok"] and reply["worker_id"] == 3

        # 7. And the engine still actually works.
        r = wire.request_reply(s, wire.FT_SUBMIT, {
            "request": wire.request_to_wire(Request(
                request_id="post-garbage", prompt_token_ids=[1, 2, 3],
                params=SamplingParams(max_tokens=2, temperature=0.0),
                arrival_time=time.monotonic())),
            "resubmit": False})
        assert r["ok"]
        for _ in range(50):
            reply = wire.request_reply(s, wire.FT_STEP, {"cancels": []})
            done = [ev for ev in reply["events"]
                    if ev["id"] == "post-garbage"
                    and "finish_reason" in ev]
            if done:
                assert done[0]["finish_reason"] == "length"
                break
        else:
            pytest.fail("request did not finish after garbage storm")
        s.close()
    finally:
        worker.close()
        t.join(timeout=10)
        assert not t.is_alive(), "worker thread must exit on close()"


class _EvilHandle:
    """A 'worker' that handshakes health correctly, then answers every
    other frame with a digest-corrupted reply."""

    def __init__(self):
        self.pid = 66666
        self._listener = socket.socket()
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(2)
        self._port = self._listener.getsockname()[1]
        self._stop = False
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            try:
                while not self._stop:
                    ftype, _ = wire.recv_frame(conn)
                    if ftype == wire.FT_HEALTH:
                        wire.send_frame(conn, wire.FT_OK, wire.pack_obj(
                            {"ok": True, "pid": self.pid, "worker_id": 0,
                             "time": 0.0, "stats": {}, "metrics": {},
                             "active": 0, "waiting": 0, "free_blocks": 64,
                             "has_work": False}))
                        continue
                    payload = wire.pack_obj({"ok": True})
                    conn.sendall(wire._HEADER.pack(
                        wire.MAGIC, wire.WIRE_VERSION, wire.FT_OK,
                        len(payload)) + payload
                        + b"\xde" * wire._DIGEST_BYTES)
            except (wire.WireError, OSError):
                pass
            finally:
                try:
                    conn.close()
                except OSError:
                    pass

    def port(self):
        return self._port

    def poll(self):
        return None if not self._stop else 0

    def wait(self, timeout=None):
        self.thread.join(timeout)
        return 0

    def terminate(self):
        self._stop = True
        try:
            self._listener.close()
        except OSError:
            pass

    kill = terminate


def test_supervisor_evicts_corrupt_peer_and_rehomes(tiny_params):
    """Worker 0 answers with digest-corrupted frames: the supervisor must
    evict it (never adopt the corrupt bytes, never hang) and finish the
    request on the healthy worker."""
    good = _thread_spawner(tiny_params)

    def spawn(idx, generation):
        if idx == 0:
            return _EvilHandle()
        return good(idx, generation)

    sup = FleetSupervisor(
        _ec(), workers=2, spawner=spawn, fleet_cfg=_fleet_cfg(),
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=False),
        canary_vocab=CFG.vocab_size)
    try:
        req = sup.submit(PROMPTS[0], GREEDY)
        deadline = time.monotonic() + 60
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
        assert req.finish_reason == "length", \
            "request must finish on the healthy worker"
        assert req.replica == 1
        assert sup.failover["replica_faults"] >= 1
        assert sup.worker_states()["0"] == "dead"  # healing off: stays dead
        assert sup.num_live == 1
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Facade surface + federation arithmetic
# ----------------------------------------------------------------------

def test_fleet_facade_and_federation(tiny_params):
    sup = _make_fleet(tiny_params, workers=2)
    try:
        sup.generate(PROMPTS, GREEDY)
        scal = sup.fleet_scalars()
        stats = sup.stats
        # Per-worker federated counters sum exactly to the fleet totals —
        # the equality loadgen's federation check asserts over /metrics.
        for k in fleet.WORKER_COUNTER_KEYS:
            worker_sum = sum(scal[f"fleet_w{i}_{k}"] for i in range(2))
            assert worker_sum == stats.get(k, 0), k
        assert scal["fleet_workers"] == 2.0
        assert scal["fleet_workers_live"] == 2.0
        assert scal["fleet_w0_up"] == 1.0 and scal["fleet_w1_up"] == 1.0
        for key in sup.fleet_gauge_keys:
            assert key in scal, key
        assert len(stats["replicas"]) == 2
        assert sup.lifecycle_counts()["live"] == 2
        assert set(sup.worker_states().values()) == {"live"}
        assert sup.respawn_retry_after_s == 0.0
        assert sup.cfg.max_seqs == 4
        assert fleet.workers_alive_gauge.value == 2.0

        # Loadgen's hardcoded key mirror must track the fleet contract.
        from dlti_tpu.benchmarks import loadgen

        assert loadgen._FLEET_COUNTER_KEYS == fleet.WORKER_COUNTER_KEYS

        # abort_all finishes every mirror and clears the pending queue.
        reqs = [sup.submit(p, SamplingParams(max_tokens=64))
                for p in PROMPTS]
        sup.step()
        aborted = sup.abort_all(reason="abort")
        assert {r.request_id for r in aborted} \
            == {r.request_id for r in reqs}
        assert all(r.finish_reason == "abort" for r in reqs)
        assert not sup.has_work
        assert sup.num_active == 0
    finally:
        sup.close()


def test_fleet_sticky_affinity_and_cancel(tiny_params):
    sup = _make_fleet(tiny_params, workers=2)
    try:
        # Same affinity key -> same worker (rendezvous hash), booked as
        # sticky routes.
        r1 = sup.submit(PROMPTS[0], GREEDY, affinity_key="session-A")
        sup.step()
        r2 = sup.submit(PROMPTS[1], GREEDY, affinity_key="session-A")
        sup.step()
        assert r1.replica == r2.replica
        assert sup.affinity["sticky"] >= 2
        # Cancellation propagates over the wire as a step piggyback.
        r3 = sup.submit(PROMPTS[2], SamplingParams(max_tokens=64))
        sup.step()
        r3.cancel_requested = True
        deadline = time.monotonic() + 30
        while sup.has_work and time.monotonic() < deadline:
            sup.step()
        # Server-side cancel finishes as a normal "stop", long before
        # max_tokens would.
        assert r3.finish_reason == "stop"
        assert len(r3.output_token_ids) < 64
    finally:
        sup.close()


# ----------------------------------------------------------------------
# Subprocess drills (slow tier): the real engine_worker.py processes
# ----------------------------------------------------------------------

def _subprocess_spec(**engine_over):
    return {
        "model_preset": "llama_tiny",
        "engine": dataclasses.asdict(_ec(**engine_over)),
        # conftest forces true-fp32 matmuls in THIS process; workers need
        # the same knob for cross-process byte identity.
        "matmul_precision": "highest",
        "warmup": False,  # lazy compiles keep the drill's boot short
    }


def _mk_subprocess_fleet(tmp_path, *, workers=2, heal=True, flight_dir=None,
                         **engine_over):
    spec = _subprocess_spec(**engine_over)
    if flight_dir:
        spec["flight_dir"] = flight_dir
    spawner = make_subprocess_spawner(spec, str(tmp_path))
    return FleetSupervisor(
        _ec(**engine_over), workers=workers, spawner=spawner,
        fleet_cfg=_fleet_cfg(workers=workers, startup_timeout_s=600.0,
                             respawn_backoff_s=0.2),
        lifecycle_cfg=ReplicaLifecycleConfig(enabled=heal,
                                             probation_initial_s=0.2),
        canary_vocab=CFG.vocab_size)


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", ["bfloat16", "int8"])
@pytest.mark.parametrize("sp", [GREEDY, SEEDED], ids=["greedy", "seeded"])
def test_subprocess_fleet_byte_identical_with_migration(
        tmp_path, tiny_params, kv_dtype, sp):
    """The acceptance drill: --fleet-workers 2 (real processes) produces
    byte-identical outputs to --replicas 2 (in-process), greedy and
    seeded, bf16 and int8 KV — including one cross-process migration."""
    ref = ReplicatedEngine(CFG, tiny_params, _ec(cache_dtype=kv_dtype),
                           replicas=2)
    expect = {tuple(p): r.output_token_ids
              for p, r in zip(PROMPTS, ref.generate(PROMPTS, sp))}

    sup = _mk_subprocess_fleet(tmp_path, workers=2, cache_dtype=kv_dtype)
    try:
        reqs = [sup.submit(p, sp) for p in PROMPTS]
        for _ in range(120):
            sup.step()
            if all(len(r.output_token_ids) >= 2 for r in reqs):
                break
        assert all(not r.done for r in reqs)
        victim = next(w for w in sup._workers if w.owned)
        errored = sup.drain_replica(victim.idx, kind="preempt",
                                    quarantine=False)
        assert errored == []
        while sup.has_work:
            sup.step()
        assert any(r.num_migrations > 0 for r in reqs)
        for p, r in zip(PROMPTS, reqs):
            assert r.output_token_ids == expect[tuple(p)], \
                f"{r.request_id} (migrations={r.num_migrations})"
            assert r.finish_reason == "length"
    finally:
        sup.close()


@pytest.mark.slow
def test_subprocess_fleet_chaos_sigkill_under_load(tmp_path):
    """Live loadgen against serve-over-fleet; SIGKILL one worker process
    mid-run. Demands: zero client errors, dlti_fleet_respawns_total >= 1,
    and federated per-worker /metrics series that sum to the fleet
    totals (LoadReport.fleet_federation)."""
    from dlti_tpu.benchmarks import LoadGenConfig, run_load_test
    from dlti_tpu.data.tokenizer import IdTokenizer
    from dlti_tpu.serving.server import ServerConfig, make_server

    from dlti_tpu.telemetry.flightrecorder import FlightRecorder, install

    flight_dir = str(tmp_path / "flight")
    # Supervisor-side recorder: _fail_worker dumps the fault at the dump
    # root; the worker processes dump under worker{N}/ (spec flight_dir).
    prev_recorder = install(FlightRecorder(flight_dir))
    sup = _mk_subprocess_fleet(tmp_path, workers=2, flight_dir=flight_dir)
    httpd = None
    try:
        httpd, async_engine = make_server(
            sup, IdTokenizer(vocab_size=CFG.vocab_size),
            ServerConfig(host="127.0.0.1", port=0,
                         default_params=SamplingParams(max_tokens=8)))
        port = httpd.server_address[1]
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()

        kill_done = threading.Event()

        def assassin():
            # Let traffic build, then SIGKILL a live worker mid-decode.
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                victims = [w for w in sup._workers
                           if w.pid and w.sock is not None and w.owned]
                if victims:
                    os.kill(victims[0].pid, signal.SIGKILL)
                    kill_done.set()
                    return
                time.sleep(0.05)

        killer = threading.Thread(target=assassin, daemon=True)
        killer.start()
        report = run_load_test(LoadGenConfig(
            host="127.0.0.1", port=port, num_requests=24, concurrency=4,
            max_tokens=8, stream=True, prompt="chaos", timeout_s=300,
            scrape_debug_vars=True))
        killer.join(timeout=60)
        assert kill_done.is_set(), "no worker was ever holding work"

        # Zero client errors through the kill + respawn.
        assert report.num_ok == report.num_requests, report.errors
        assert report.errors == []

        # The killed worker respawned.
        deadline = time.monotonic() + 120
        while sup._respawns < 1 and time.monotonic() < deadline:
            time.sleep(0.2)
        assert sup._respawns >= 1
        assert fleet.respawns_total.value >= 1

        # Federation: per-worker series were scraped and sum to totals.
        fed = report.fleet_federation
        assert fed, "fleet federation block missing from LoadReport"
        assert sorted(fed["workers"]) == [0, 1]
        assert fed["consistent"], fed["checks"]
        assert fed["respawns_total"] >= 1

        # Satellite: postmortem --all merges the per-worker dump tree
        # (the SIGKILL'd worker's supervisor-side dump is at the root).
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(__file__),
                                        "..", "scripts"))
        try:
            import postmortem
            dumps = postmortem.discover_dumps(flight_dir)
        finally:
            sys.path.pop(0)
        assert dumps, "worker fault must leave a flight dump"
    finally:
        install(prev_recorder)
        if httpd is not None:
            httpd.shutdown()
            async_engine.shutdown()
            httpd.server_close()
        sup.close()
