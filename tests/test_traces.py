"""Trace format + replay tests (dlti_tpu.benchmarks.traces / loadgen).

Three contracts pinned here:

1. **Byte determinism** — the same seed yields a byte-identical trace
   file (sorted-key compact JSON, µs-rounded offsets), so committed
   traces are diffable fixtures and drills are reproducible.
2. **Replay fidelity** — loadgen's ``--trace`` drive fires each event at
   (or just after, never before) its recorded offset; ``--record-trace``
   of a replay round-trips the workload descriptors unchanged.
3. **Live agreement** — ``LoadReport.slo``'s client-side recomputation
   of the server's objectives matches ``GET /debug/slo`` within 1% per
   (objective, class) pair, end-to-end against a real tiny-model server.
"""

import json
import sys
import threading

import pytest

from dlti_tpu.benchmarks.loadgen import LoadGenConfig, run_load_test
from dlti_tpu.benchmarks.traces import (
    GENERATORS, TRACE_FORMAT, TraceEvent, main as traces_main, read_trace,
    synthesize, trace_summary, write_trace,
)
from dlti_tpu.serving.wire import ephemeral_port as _free_dead_port


# ----------------------------------------------------------------------
# Format: determinism, round-trip, schema tolerance
# ----------------------------------------------------------------------

def test_same_seed_byte_identical_files(tmp_path):
    a, b = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    for path in (a, b):
        meta, events = synthesize("flash_crowd", duration_s=10.0, rate=8.0,
                                  seed=7, session_frac=0.3,
                                  adapters=("lora-a", "lora-b"),
                                  adapter_frac=0.25)
        write_trace(str(path), events, meta)
    assert a.read_bytes() == b.read_bytes()
    assert a.stat().st_size > 0
    # ... and a different seed is actually a different trace.
    meta, events = synthesize("flash_crowd", duration_s=10.0, rate=8.0,
                              seed=8)
    c = tmp_path / "c.jsonl"
    write_trace(str(c), events, meta)
    assert a.read_bytes() != c.read_bytes()


def test_write_read_round_trip_sorts_and_rounds(tmp_path):
    path = tmp_path / "t.jsonl"
    events = [
        TraceEvent(offset_s=2.0000004, prompt_tokens=10, max_tokens=4,
                   tenant="t1", priority="batch", session="t1/s0",
                   adapter="lora-x", deadline_s=1.5),
        TraceEvent(offset_s=0.5, prompt_tokens=3, max_tokens=2),
    ]
    write_trace(str(path), events, meta={"generator": "hand", "seed": 0})
    header, back = read_trace(str(path))
    assert header["format"] == TRACE_FORMAT
    assert header["num_events"] == 2
    assert header["generator"] == "hand"
    # Events come back offset-sorted with µs-rounded offsets; every
    # workload descriptor survives the trip.
    assert [e.offset_s for e in back] == [0.5, 2.0]
    assert back[0] == events[1]
    e = back[1]
    assert (e.prompt_tokens, e.max_tokens) == (10, 4)
    assert (e.tenant, e.priority, e.session, e.adapter) == \
        ("t1", "batch", "t1/s0", "lora-x")
    assert e.deadline_s == 1.5


def test_from_dict_ignores_unknown_keys_so_format_can_grow():
    e = TraceEvent.from_dict({"offset_s": 1.0, "prompt_tokens": 2,
                              "max_tokens": 3, "some_future_field": "x"})
    assert (e.offset_s, e.prompt_tokens, e.max_tokens) == (1.0, 2, 3)
    assert e.tenant == "t0" and e.priority == "interactive"


def test_headerless_file_gets_synthesized_header(tmp_path):
    path = tmp_path / "bare.jsonl"
    path.write_text(json.dumps({"offset_s": 0.25, "prompt_tokens": 5,
                                "max_tokens": 6}) + "\n")
    header, events = read_trace(str(path))
    assert header["format"] == TRACE_FORMAT
    assert header["num_events"] == 1
    assert events[0].offset_s == 0.25


# ----------------------------------------------------------------------
# Generators
# ----------------------------------------------------------------------

def test_generators_produce_well_formed_events():
    for gen in GENERATORS:
        meta, events = synthesize(gen, duration_s=20.0, rate=6.0, seed=3,
                                  session_frac=0.5)
        assert meta["generator"] == gen and meta["seed"] == 3
        assert events, gen
        offsets = [e.offset_s for e in events]
        assert offsets == sorted(offsets)
        assert 0.0 <= offsets[0] and offsets[-1] < 20.0
        for e in events:
            assert e.prompt_tokens >= 1 and e.max_tokens >= 1
            assert e.priority in ("interactive", "batch")
            if e.session:
                assert e.session.startswith(e.tenant + "/")


def test_flash_crowd_surges_inside_the_burst_window():
    meta, events = synthesize("flash_crowd", duration_s=60.0, rate=4.0,
                              seed=11, flash_at_s=20.0,
                              flash_duration_s=10.0, flash_factor=8.0)
    assert meta["flash_at_s"] == 20.0 and meta["flash_factor"] == 8.0
    in_burst = sum(1 for e in events if 20.0 <= e.offset_s < 30.0)
    before = sum(1 for e in events if e.offset_s < 20.0)
    burst_rate = in_burst / 10.0
    base_rate = before / 20.0
    # 8x surge with a fixed seed: well clear of a 3x statistical wobble.
    assert burst_rate > 3.0 * base_rate, (burst_rate, base_rate)


def test_zipf_tenants_skew_toward_t0():
    _, events = synthesize("poisson", duration_s=60.0, rate=8.0, seed=5,
                           tenants=4, zipf_alpha=1.1)
    counts = {}
    for e in events:
        counts[e.tenant] = counts.get(e.tenant, 0) + 1
    assert set(counts) <= {"t0", "t1", "t2", "t3"}
    assert counts["t0"] == max(counts.values())


def test_trace_summary_shape():
    assert trace_summary([]) == {"num_events": 0}
    _, events = synthesize("poisson", duration_s=30.0, rate=6.0, seed=2,
                           interactive_frac=0.8)
    s = trace_summary(events)
    assert s["num_events"] == len(events)
    assert 0.0 <= s["interactive_frac"] <= 1.0
    assert s["tenants"] >= 1 and s["top_tenant_frac"] <= 1.0
    assert s["mean_prompt_tokens"] >= 1


def test_cli_main_writes_readable_trace(tmp_path, capsys, monkeypatch):
    out = tmp_path / "cli.jsonl"
    monkeypatch.setattr(sys, "argv", [
        "traces", "--out", str(out), "--generator", "flash_crowd",
        "--duration-s", "8", "--rate", "6", "--seed", "4"])
    traces_main()
    header, events = read_trace(str(out))
    assert header["generator"] == "flash_crowd" and events
    printed = json.loads(capsys.readouterr().out)
    assert printed["num_events"] == len(events)


# ----------------------------------------------------------------------
# Replay (no server needed: a dead port refuses fast; the dispatch
# timing and --record-trace capture happen client-side regardless)
# ----------------------------------------------------------------------

def test_replay_offsets_faithful_and_never_early(tmp_path):
    src = tmp_path / "src.jsonl"
    out = tmp_path / "rerecorded.jsonl"
    meta, events = synthesize("poisson", duration_s=1.5, rate=8.0, seed=9,
                              session_frac=0.5)
    assert events
    write_trace(str(src), events, meta)
    report = run_load_test(LoadGenConfig(
        host="127.0.0.1", port=_free_dead_port(), trace=str(src),
        record_trace=str(out), concurrency=64, timeout_s=2.0,
        scrape_server_metrics=False, scrape_debug_vars=False))
    # Every event was submitted (the dead port errors them, but the
    # submission — and its capture — happened).
    assert report.num_requests == len(events)
    header, rec = read_trace(str(out))
    assert header["mode"] == "replay" and header["source"] == "loadgen"
    assert len(rec) == len(events)
    for s, r in zip(events, rec):
        # Never ahead of the recorded arrival; close behind it (the
        # dispatch loop sleeps to the offset, then stamps at task start).
        assert r.offset_s >= s.offset_s - 1e-3, (s.offset_s, r.offset_s)
        assert r.offset_s - s.offset_s < 1.0, (s.offset_s, r.offset_s)
        # Workload descriptors round-trip through the replay body.
        assert (r.tenant, r.priority, r.session) == \
            (s.tenant, s.priority, s.session)
        assert r.prompt_tokens == s.prompt_tokens
        assert r.max_tokens == s.max_tokens


# ----------------------------------------------------------------------
# Live agreement: LoadReport.slo vs GET /debug/slo on a real server
# ----------------------------------------------------------------------

@pytest.fixture(scope="module")
def slo_server():
    import jax
    import jax.numpy as jnp

    from dlti_tpu.config import MODEL_PRESETS, SLOConfig, TelemetryConfig
    from dlti_tpu.data.tokenizer import ByteTokenizer
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.serving import EngineConfig, InferenceEngine, SamplingParams
    from dlti_tpu.serving.server import ServerConfig, make_server

    cfg = MODEL_PRESETS["llama_tiny"]
    model = LlamaForCausalLM(cfg, None)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    ec = EngineConfig(max_seqs=4, block_size=8, num_blocks=128,
                      max_model_len=128, cache_dtype="float32",
                      eos_token_id=-1)
    engine = InferenceEngine(cfg, params, ec)
    # Generous thresholds + an hour-long budget window: on CPU every
    # request is "good", so server and client both report 100% and the
    # agreement check exercises the full pipeline without flakiness.
    tel = TelemetryConfig(slo=SLOConfig(
        enabled=True, window_s=3600.0, ttft_threshold_s=30.0,
        ttft_target=0.5, tpot_threshold_s=30.0, tpot_target=0.5))
    httpd, async_engine = make_server(
        engine, ByteTokenizer(),
        ServerConfig(host="127.0.0.1", port=0, telemetry=tel,
                     default_params=SamplingParams(max_tokens=8)))
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield "127.0.0.1", port
    httpd.shutdown()
    httpd.sampler.stop()
    async_engine.shutdown()
    httpd.server_close()


def test_debug_slo_endpoint_live(slo_server):
    import http.client

    host, port = slo_server
    conn = http.client.HTTPConnection(host, port, timeout=30)
    conn.request("GET", "/debug/slo")
    resp = conn.getresponse()
    body = json.loads(resp.read())
    conn.close()
    assert resp.status == 200
    assert set(body["objectives"]) == {"ttft/all", "tpot/all"}
    assert body["objectives"]["ttft/all"]["objective"] == "ttft"
    assert body["window_s"] == 3600.0
    assert isinstance(body["burn_tiers"], list) and body["burn_tiers"]


def test_loadreport_slo_matches_debug_slo(slo_server):
    host, port = slo_server
    report = run_load_test(LoadGenConfig(
        host=host, port=port, num_requests=12, concurrency=4,
        max_tokens=8, stream=True, prompt="agreement check prompt",
        scrape_server_metrics=False))
    assert not report.errors and report.num_ok == 12
    assert report.slo, "server advertises SLOs; LoadReport.slo must fill"
    # Per-pair server-vs-client agreement within 1% — the acceptance
    # bar for the whole cross-check (ISSUE acceptance criterion).
    assert report.slo["max_delta"] <= 0.01, report.slo["agreement"]
    agreement = report.slo["agreement"]
    assert set(agreement) == {"ttft/all", "tpot/all"}
    for key, pair in agreement.items():
        assert pair["server"] == pytest.approx(pair["client"], abs=0.01)
    assert report.slo["breaching"] == []
    for key, srv in report.slo["server"].items():
        assert srv["error_budget_remaining"] == pytest.approx(1.0, abs=0.05)
