"""REAL multi-process distributed training (not simulated).

Everything else in this suite simulates N devices inside one process. This
test spawns TWO actual worker processes via ``scripts/launch.py`` (the
torchrun / deepspeed-CLI analog), each owning 4 virtual CPU devices; they
rendezvous through ``jax.distributed.initialize`` (gloo CPU collectives)
into one 8-device ZeRO-3 mesh, train llama_tiny on known global batches,
and the losses must match a single-device run of the same math — the
capability the reference exercised with real multi-rank jobs
(``train.ipynb:640-653``; its 2-GPU crash at ``:794-838`` is what happens
without an equivalence test like this one).
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _reference_losses(n_steps: int):
    """Single-device ground truth on the worker's exact batch/rng schedule."""
    from dlti_tpu.config import (
        Config, LoRAConfig, MODEL_PRESETS, OptimizerConfig, ParallelConfig,
        TrainConfig,
    )
    from dlti_tpu.models import LlamaForCausalLM
    from dlti_tpu.training import (
        build_optimizer, create_train_state, make_train_step,
    )

    cfg = Config(
        model=MODEL_PRESETS["llama_tiny"],
        lora=LoRAConfig(r=4, alpha=8, dropout=0.0),
        optimizer=OptimizerConfig(warmup_steps=2),
        parallel=ParallelConfig(),
        train=TrainConfig(micro_batch_size=8, grad_accum_steps=2),
    )
    rng = jax.random.PRNGKey(0)
    model = LlamaForCausalLM(cfg.model, cfg.lora)
    tx = build_optimizer(cfg.optimizer)
    state = create_train_state(rng, model, tx, (2, 32), lora_enabled=True)
    step = jax.jit(make_train_step(model, accum_steps=2))

    accum, bs, seq = 2, 8, 32
    np_rng = np.random.default_rng(7)
    batch = {
        "input_ids": np_rng.integers(
            0, cfg.model.vocab_size, (accum, bs, seq)).astype(np.int32),
        "loss_mask": np.ones((accum, bs, seq), np.int32),
    }
    losses = []
    for i in range(n_steps):
        state, metrics = step(state, batch, jax.random.fold_in(rng, i))
        losses.append(float(jax.device_get(metrics["loss"])))
    return losses


@pytest.mark.parametrize("strategy", ["zero3", "tp", "pipe"])
def test_two_process_mesh_matches_single_device(tmp_path, strategy):
    n_steps = 4
    out = tmp_path / "rank0.json"
    env = dict(os.environ)
    # The workers set their own XLA_FLAGS/platform; scrub the test
    # harness's 8-device forcing so each worker sees its own 4.
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "launch.py"),
         "--num-processes", "2", "--log-dir", str(tmp_path / "logs"), "--",
         sys.executable, os.path.join(REPO, "tests", "dist_worker.py"),
         str(out), str(n_steps), strategy],
        env=env, capture_output=True, text=True, timeout=900, cwd=REPO)
    logs = ""
    for rank in (0, 1):
        p = tmp_path / "logs" / f"rank{rank}.err"
        if p.exists():
            logs += f"--- rank{rank}.err ---\n" + p.read_text()[-2000:]
    assert proc.returncode == 0, f"launcher rc={proc.returncode}\n{logs}"
    assert out.exists(), f"rank0 wrote no output\n{logs}"

    got = json.loads(out.read_text())
    assert got["process_count"] == 2
    assert got["device_count"] == 8
    expected = _reference_losses(n_steps)
    np.testing.assert_allclose(
        got["losses"], expected, rtol=2e-4,
        err_msg="2-process distributed losses diverged from single-device")
