"""Headline benchmark: LoRA-SFT training throughput on the local TPU chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N, ...}

Baseline: the reference's only recorded training throughput — ZeRO-2,
Llama-2-7B LoRA, micro-bs=1, seq<=512 on one V100-SXM2-32GB at ~2.93 it/s
steady state (BASELINE.md; train.ipynb:442,524,607), i.e. ~1500 tok/s.

We run the same workload (Llama-2-7B + LoRA r=16 on q/k/v/o, seq 512,
AdamW + warmup + clip 1.0, remat) on one TPU chip at the largest micro-batch
that fits, and report achieved tokens/sec/chip. ``vs_baseline`` > 1 means
faster than the reference's V100 number. If the flagship model cannot fit
(e.g. small-HBM dev chip), we fall back to a smaller preset and normalize
the comparison by model FLOPs (reported transparently via ``model`` /
``flops_normalized`` keys).

Env overrides: BENCH_MODEL (preset name), BENCH_BS, BENCH_SEQ, BENCH_STEPS.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.abspath(__file__))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root

# ---------------------------------------------------------------------------
# Driver-proofing (round-3 postmortem: BENCH_r03.json rc=124/parsed=null).
#
# The r03 bench burned its whole budget because backend *initialization*
# failed — each of the 11 candidates re-paid a ~25-minute UNAVAILABLE stall
# before raising, and the driver killed the process before any JSON was
# printed. Three guards make that impossible now:
#   1. a bounded subprocess probe of jax.devices() BEFORE importing jax
#      here (failure -> error JSON + nonzero exit in ~minutes, not hours);
#   2. a stale-process sweep between probe attempts (a leftover serving /
#      bench process holding the chip is the prime suspect for r03);
#   3. a watchdog thread with a hard deadline that prints best-so-far (or
#      an error JSON) and exits, so the driver ALWAYS gets a JSON line.
# ---------------------------------------------------------------------------

PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", 300))
DEADLINE_S = int(os.environ.get("BENCH_DEADLINE_S", 1800))
# Slack reserved past the last probe attempt so a late success still has
# time to compile + run one candidate before the watchdog fires.
MIN_SLACK_S = int(os.environ.get("BENCH_MIN_SLACK_S", 300))
_START = time.monotonic()
_BEST = {}  # filled by main(); read by the watchdog on deadline


_EMIT_LOCK = threading.Lock()


def _emit(obj) -> bool:
    """Print the ONE official JSON line. Exactly one call wins — main and
    the watchdog both funnel through here, so a deadline firing while main
    is mid-emit can never double-print."""
    with _EMIT_LOCK:
        if _BEST.get("printed"):
            return False
        _BEST["printed"] = True
        print(json.dumps(obj), flush=True)
        return True


def _error_json(msg: str):
    return {"metric": "lora_sft_tokens_per_sec_per_chip", "value": 0.0,
            "unit": "tok/s/chip", "vs_baseline": 0.0, "error": msg}


def _kill_stale_chip_holders(min_age_s: float = 3600.0,
                             sig: int = signal.SIGKILL) -> list:
    """Signal leftover python processes from a previous builder session
    (serving servers, benchmarks, trainers) that may still hold the TPU.

    Only targets processes whose cmdline references this repo's entry
    points AND that are older than ``min_age_s`` (default 1 h — longer
    than any healthy workload here, including 15-min serving benchmarks,
    while a builder-session leftover is hours old by driver time). Never
    touches self, ancestors, or non-python processes. Disable entirely
    with BENCH_NO_KILL=1.
    """
    if os.environ.get("BENCH_NO_KILL") == "1":
        return []
    me = os.getpid()
    ancestors = set()
    pid = me
    for _ in range(16):
        try:
            with open(f"/proc/{pid}/stat") as f:
                pid = int(f.read().split(")")[-1].split()[1])  # ppid
            ancestors.add(pid)
        except Exception:
            break
    try:
        with open("/proc/uptime") as f:
            uptime = float(f.read().split()[0])
        clk = os.sysconf("SC_CLK_TCK")
    except Exception:
        return []
    needles = ("dlti_tpu", "bench.py", "scripts/serve", "scripts/train",
               "benchmark_serving", "run_experiments")
    killed = []
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        pid = int(d)
        if pid == me or pid in ancestors:
            continue
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().decode("utf-8", "replace").replace("\0", " ")
            with open(f"/proc/{pid}/stat") as f:
                start_ticks = int(f.read().split(")")[-1].split()[19])
        except Exception:
            continue
        age_s = uptime - start_ticks / clk
        if "python" not in cmd or age_s < min_age_s:
            continue
        if any(n in cmd for n in needles):
            try:
                os.kill(pid, sig)
                killed.append((pid, round(age_s), cmd[:120]))
            except Exception:
                pass
    if killed:
        print(f"# bench: killed stale chip holders: {killed}",
              file=sys.stderr, flush=True)
    return killed


def _sweep_stale_holders(min_age_s: float = 3600.0) -> list:
    """SIGTERM-then-SIGKILL wrapper around the holder scan: gives a healthy
    long-running job (e.g. a serving benchmark that outlived 1 h during a
    relay outage) a 10 s window to flush results and release the chip
    cleanly before the hard kill. A probe failure does not prove a process
    holds the chip — the relay itself may be down — so the polite signal
    first is the cheap insurance."""
    termed = _kill_stale_chip_holders(min_age_s=min_age_s, sig=signal.SIGTERM)
    if termed:
        time.sleep(10)
        _kill_stale_chip_holders(min_age_s=min_age_s, sig=signal.SIGKILL)
    return termed


def _probe_backend() -> None:
    """Verify jax.devices() works in a bounded subprocess before committing
    this process to backend init. Exits with an error JSON on failure."""
    # A site hook in this image re-forces the TPU plugin platform on jax
    # import; the env var alone is ignored, so honor it via jax.config
    # (same trick as tests/conftest.py) — lets CI/CPU runs probe cheaply.
    code = ("import os, jax; p = os.environ.get('JAX_PLATFORMS');\n"
            "p and jax.config.update('jax_platforms', p)\n"
            "ds = jax.devices(); print('PROBE_OK', len(ds), ds[0].platform)")
    # Retry until the watchdog deadline minus candidate slack: the relay
    # flaps on a multi-hour period, so a recovery anywhere inside the
    # driver's window must convert into a measurement, not a forfeit
    # (r04 lesson: exiting after 2 attempts gave back 1200 s of budget).
    attempt = 0
    detail = "?"
    while True:
        remaining = DEADLINE_S - (time.monotonic() - _START)
        # Always probe at least once, even with a deadline below the
        # slack floor (a smoke run with BENCH_DEADLINE_S=240 must probe,
        # not exit "failed 0x" against a healthy backend).
        if remaining < MIN_SLACK_S and attempt >= 1:
            break
        attempt += 1
        t0 = time.monotonic()
        # Clamp so even the last attempt returns control before the
        # slack boundary — the loop (not the watchdog) must emit the
        # rc=3 JSON. Exception: the guaranteed FIRST probe. With a
        # deadline below the slack floor (BENCH_DEADLINE_S < MIN_SLACK_S,
        # the smoke case), remaining - MIN_SLACK_S clamps to the 10 s
        # floor — too short for real backend init on a slow-init relay,
        # so a healthy backend would be reported as 'failed 1x' in
        # exactly the scenario the always-probe-once rule covers. Give
        # that first probe the full remaining budget instead.
        slack_bounded = remaining - MIN_SLACK_S
        if attempt == 1 and slack_bounded < 10:
            probe_t = min(PROBE_TIMEOUT_S, max(10, remaining))
        else:
            probe_t = min(PROBE_TIMEOUT_S, max(10, slack_bounded))
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=probe_t)
        except subprocess.TimeoutExpired:
            r = None
        dt = time.monotonic() - t0
        if r is not None and r.returncode == 0 and "PROBE_OK" in r.stdout:
            print(f"# bench: backend probe ok in {dt:.0f}s (attempt "
                  f"{attempt}): {r.stdout.strip().splitlines()[-1]}",
                  file=sys.stderr, flush=True)
            return
        detail = ("timeout" if r is None
                  else (r.stderr.strip().splitlines() or ["?"])[-1][:300])
        print(f"# bench: backend probe attempt {attempt} failed "
              f"({dt:.0f}s): {detail}", file=sys.stderr, flush=True)
        # Sweep stale holders on the first failure, then every ~10 min of
        # the retry window: a process that crosses the 1 h age threshold
        # MID-window must still get swept, or it blocks every remaining
        # attempt.
        if attempt == 1 or time.monotonic() - _BEST.get("swept_at", 0) > 600:
            _BEST["swept_at"] = time.monotonic()
            _sweep_stale_holders()
        # A failed probe usually burns its full timeout already; a short
        # pause between fast failures avoids a tight spin when the relay
        # rejects connections immediately. Never sleep past the slack
        # boundary — the loop (not the watchdog) must emit the rc=3 JSON.
        remaining = DEADLINE_S - (time.monotonic() - _START)
        pause = min(30 - dt, remaining - MIN_SLACK_S - 5)
        if pause > 0:
            time.sleep(pause)
    _emit(_error_json(
        f"backend probe failed {attempt}x until {MIN_SLACK_S}s slack "
        f"(probe_timeout={PROBE_TIMEOUT_S}s): {detail}"))
    sys.exit(3)


def _watchdog() -> None:
    """Hard deadline: whatever happens (hung probe, hung compile, relay
    stall), print a JSON line and exit before the driver's timeout turns it
    into rc=124. Runs from BEFORE the backend probe so even a probe stuck
    in an uninterruptible wait is covered."""
    remaining = DEADLINE_S - (time.monotonic() - _START)
    if remaining > 0:
        time.sleep(remaining)
    if _BEST.get("printed"):
        return  # main already emitted; let its own exit path finish
    # Bounded lock acquire: if main is itself wedged inside print() while
    # holding the lock (blocked stdout), exit anyway — holding the process
    # open can only end in the driver's rc=124.
    got = _EMIT_LOCK.acquire(timeout=15)
    code = 4
    try:
        if not _BEST.get("printed"):
            obj = _BEST.get("json") or _error_json(
                f"deadline {DEADLINE_S}s hit with no completed candidate; "
                f"last: {_BEST.get('last_candidate')}")
            _BEST["printed"] = True
            print(json.dumps(obj), flush=True)
            code = 0 if "error" not in obj else 4
        else:
            code = 0
    finally:
        if got:
            _EMIT_LOCK.release()
        os._exit(code)


# Watchdog first (it must cover a hung probe), then the bounded probe.
threading.Thread(target=_watchdog, daemon=True).start()
if os.environ.get("BENCH_SKIP_PROBE") != "1":
    _probe_backend()

try:
    import jax  # noqa: E402  (post-probe: backend known reachable)
    import jax.numpy as jnp  # noqa: E402

    # honor_platform_env re-asserts JAX_PLATFORMS past the site hook (same
    # override the probe used) and enables the persistent compile cache.
    from dlti_tpu.utils.platform import honor_platform_env  # noqa: E402

    honor_platform_env()
except BaseException as e:  # driver contract: ALWAYS one JSON line
    _emit(_error_json(f"init: {type(e).__name__}: {str(e)[:300]}"))
    raise

V100_BASELINE_TOK_S = 2.93 * 512  # ~1500 tok/s (BASELINE.md)
SEQ = int(os.environ.get("BENCH_SEQ", 512))
STEPS = int(os.environ.get("BENCH_STEPS", 10))

# In-process anomaly watchdog over the measured loop (telemetry.watchdog):
# each timed step feeds notify_step, so a wedged relay/compile mid-candidate
# trips the hung-step rule and the final JSON carries `watchdog_alerts` —
# chaos/regression consumers fail loudly instead of trusting a clean-looking
# number. The deadline floor is generous (BENCH_HUNG_STEP_S, default 600 s)
# so 7B cold compiles never false-positive.
_WATCHDOG = None


def _start_watchdog():
    global _WATCHDOG
    try:
        from dlti_tpu.config import WatchdogConfig
        from dlti_tpu.telemetry import AnomalyWatchdog, TimeSeriesSampler

        _WATCHDOG = AnomalyWatchdog(
            WatchdogConfig(
                enabled=True,
                hung_step_min_s=float(os.environ.get("BENCH_HUNG_STEP_S",
                                                     600))),
            TimeSeriesSampler(interval_s=5.0))
        _WATCHDOG.start()
    except Exception as e:  # the bench must run even if telemetry breaks
        print(f"# bench: watchdog unavailable: {e}", file=sys.stderr,
              flush=True)


def _try_run(model_name: str, micro_bs: int, quant: str = "",
             remat_policy: str = "", remat_stride: int = 0,
             loss_chunk: int = 0, sync: int = 1):
    import dataclasses

    from dlti_tpu.config import MODEL_PRESETS, LoRAConfig, OptimizerConfig
    from dlti_tpu.models import LlamaForCausalLM, count_params
    from dlti_tpu.training import build_optimizer, create_train_state, make_train_step

    if quant not in ("", "int8"):
        raise ValueError(f"unknown BENCH_QUANT={quant!r} (only '' or 'int8')")
    cfg = MODEL_PRESETS[model_name]
    overrides = {}
    if remat_policy == "none":
        overrides["remat"] = False
    elif remat_policy:
        overrides["remat_policy"] = remat_policy
    if remat_stride:
        overrides["remat_stride"] = remat_stride
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    model = LlamaForCausalLM(cfg, LoRAConfig())
    tx = build_optimizer(OptimizerConfig())
    rng = jax.random.PRNGKey(0)
    state = create_train_state(rng, model, tx, (micro_bs, SEQ))
    jax.block_until_ready(jax.tree_util.tree_leaves(state.params)[0])
    trainable, total = count_params(state.params)
    if quant == "int8":
        # Frozen-base weight-only int8 (TrainConfig.quantize_frozen_base):
        # halves base-weight HBM so activation saving fits.
        from dlti_tpu.models.quantization import quantize_params_int8

        state = state.replace(
            params=quantize_params_int8(state.params, donate=True))
        jax.block_until_ready(jax.tree_util.tree_leaves(state.params)[0])

    base_step = make_train_step(model, accum_steps=1, loss_chunk=loss_chunk)
    batch = {
        "input_ids": jax.random.randint(rng, (1, micro_bs, SEQ), 0, cfg.vocab_size),
        "loss_mask": jnp.ones((1, micro_bs, SEQ), jnp.int32),
    }
    # Warmup (compile + 2 calls). NOTE: on the remote-relay PJRT backend in
    # this image, jax.block_until_ready returns before device work finishes,
    # so all timing synchronizes via device_get (a real data dependency) —
    # slightly pessimistic (no host/device pipelining) but honest.
    if sync > 1:
        # Trainer's steps_per_sync path (the same make_multi_step the
        # Trainer scans): `sync` whole optimizer steps per compiled
        # program, one host sync per window — amortizes the fixed
        # per-call dispatch/relay round-trip.
        from dlti_tpu.training import make_multi_step

        step = make_multi_step(base_step)
        batches = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (sync,) + x.shape), batch)

        def run(state, i):
            rngs = jax.vmap(
                lambda j: jax.random.fold_in(rng, i * sync + j)
            )(jnp.arange(sync))
            state, ms = step(state, batches, rngs)
            return state, float(jax.device_get(ms["loss"][-1]))
    else:
        step = jax.jit(base_step, donate_argnums=(0,))

        def run(state, i):
            state, m = step(state, batch, jax.random.fold_in(rng, i))
            return state, float(jax.device_get(m["loss"]))

    # Warmup (indices past the timed range: fold_in rejects negatives).
    state, loss_val = run(state, STEPS)
    state, loss_val = run(state, STEPS + 1)

    # Goodput ledger over the measured loop (telemetry.ledger): books the
    # dispatch+sync of each compiled call as productive step compute and
    # everything between as host overhead, so the BENCH JSON records
    # attribution (goodput_fraction + bucket totals), not just tok/s.
    from dlti_tpu.telemetry import GoodputLedger, MemoryLedger

    # Memory ledger over the measured loop (telemetry.memledger): the
    # BENCH JSON records where HBM went (params vs optimizer vs
    # untracked) alongside where the wall clock went — an OOM'd candidate
    # and a fit-with-headroom one must be distinguishable from the line.
    memledger = MemoryLedger()
    state_box = {"state": state}
    memledger.register("params", lambda: state_box["state"].params)
    memledger.register("optimizer_state",
                       lambda: state_box["state"].opt_state)

    ledger = GoodputLedger()
    t0 = time.perf_counter()
    for i in range(STEPS):
        ledger.enter("step_compute")
        state, loss_val = run(state, i)
        state_box["state"] = state
        ledger.enter("other")
        if _WATCHDOG is not None:
            _WATCHDOG.notify_step(i)
    dt = (time.perf_counter() - t0) / (STEPS * sync)
    tok_s = micro_bs * SEQ / dt
    goodput = ledger.to_dict()
    snap = memledger.snapshot()
    memory = {
        "source": snap["source"],
        "bytes_in_use": snap["bytes_in_use"],
        "peak_bytes": snap["peak_bytes"],
        "untracked_bytes": snap["untracked_bytes"],
        "owners": {o: d["bytes"] for o, d in snap["owners"].items()},
    }
    return tok_s, dt, trainable, total, loss_val, goodput, memory


def main() -> None:
    from dlti_tpu.utils.metrics import compute_mfu, detect_chip_peak_flops

    _start_watchdog()

    if "BENCH_MODEL" in os.environ:
        quant = os.environ.get("BENCH_QUANT", "")
        if quant not in ("", "int8"):
            # Fail loudly but WITH a JSON line (the driver contract): the
            # try-loop below treats exceptions as OOMs and would burn
            # candidates on a config typo.
            _emit(_error_json(
                f"unknown BENCH_QUANT={quant!r} (only '' or 'int8')"))
            sys.exit(2)
        candidates = [dict(model=os.environ["BENCH_MODEL"],
                           bs=int(os.environ.get("BENCH_BS", 1)),
                           quant=quant,
                           remat_policy=os.environ.get("BENCH_REMAT", ""),
                           remat_stride=int(os.environ.get("BENCH_STRIDE", 0)),
                           loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", 0)),
                           sync=int(os.environ.get("BENCH_SYNC", 1)))]
    else:
        # Ordered by measured throughput on the v5e-class 16 GB chip
        # (results/mfu_investigation_r03.json): int8 frozen base frees
        # ~6.7 GB of base-weight HBM so remat can be disabled entirely
        # (the binding constraint at bf16 —
        # results/mfu_investigation_r02.json), and steps_per_sync scans
        # whole optimizer steps into one compiled call, amortizing the
        # fixed dispatch/relay round-trip. Winner: 65.1% MFU / 4,746
        # tok/s at int8 bs4 no-remat sync=20 (vs 40.8% bf16 in r02).
        candidates = [
            dict(model="llama2_7b", bs=4, quant="int8", remat_policy="none",
                 sync=20),
            dict(model="llama2_7b", bs=4, quant="int8", remat_policy="none",
                 sync=10),
            dict(model="llama2_7b", bs=4, quant="int8", remat_policy="none"),
            dict(model="llama2_7b", bs=4, quant="int8",
                 remat_policy="dots_with_no_batch_dims_saveable"),
            dict(model="llama2_7b", bs=4, quant="int8",
                 remat_policy="dots_saveable"),
            dict(model="llama2_7b", bs=8, quant="int8",
                 remat_policy="save_attn_out", remat_stride=4),
            dict(model="llama2_7b", bs=4, quant="int8"),
            dict(model="llama2_7b", bs=4),
            dict(model="llama2_7b", bs=2),
            dict(model="llama2_7b", bs=1),
            dict(model="llama_1b", bs=8),
        ]

    result = None
    failures = []
    out_of_time = False
    # Leave enough slack (module-level MIN_SLACK_S) for one more
    # candidate's compile+run before the watchdog deadline; otherwise stop
    # and report what we have.
    for c in candidates:
        remaining = DEADLINE_S - (time.monotonic() - _START)
        if remaining < MIN_SLACK_S:
            print(f"# bench: {remaining:.0f}s left < {MIN_SLACK_S}s slack; "
                  f"stopping candidate loop", file=sys.stderr, flush=True)
            out_of_time = True
            break
        _BEST["last_candidate"] = c
        try:
            tok_s, dt, trainable, total, loss, goodput, memory = _try_run(
                c["model"], c["bs"], quant=c.get("quant", ""),
                remat_policy=c.get("remat_policy", ""),
                remat_stride=c.get("remat_stride", 0),
                loss_chunk=c.get("loss_chunk", 0),
                sync=c.get("sync", 1))
            result = (c, tok_s, dt, trainable, total, loss, goodput, memory)
            # Minimal best-so-far for the watchdog: if anything after the
            # loop stalls (e.g. a device query in MFU derivation), the
            # deadline still emits a real measurement, not an error.
            _BEST["json"] = {
                "metric": "lora_sft_tokens_per_sec_per_chip_llama2_7b_seq512",
                "value": round(tok_s, 1), "unit": "tok/s/chip",
                "vs_baseline": round(tok_s / V100_BASELINE_TOK_S, 3),
                "model": c["model"], "micro_batch_size": c["bs"],
                "partial": "post-measurement finalization stalled"}
            break
        except Exception as e:  # OOM or compile failure: try the next config
            msg = f"{type(e).__name__}: {str(e)[:200]}"
            failures.append({"candidate": c, "error": msg})
            print(f"# bench: {c} failed: {msg}", file=sys.stderr, flush=True)
            continue
    if result is None:
        why = ("deadline slack exhausted before any candidate completed"
               if out_of_time else "no config fit")
        _emit(_error_json(f"{why} ({len(failures)} candidates failed; "
                          f"first: {failures[0] if failures else None})"))
        sys.exit(5)

    c, tok_s, dt, trainable, total, loss, goodput, memory = result
    model_name, bs = c["model"], c["bs"]
    peak = detect_chip_peak_flops()
    mfu = compute_mfu(tok_s, total, peak, trainable_params=trainable)

    # FLOPs-normalize if we had to fall back below 7B so vs_baseline stays an
    # apples-to-apples compute-rate comparison.
    from dlti_tpu.config import MODEL_PRESETS

    n7b = MODEL_PRESETS["llama2_7b"].num_params()
    normalized = model_name != "llama2_7b"
    eff_tok_s = tok_s * (total / n7b) if normalized else tok_s

    out = {
        "metric": "lora_sft_tokens_per_sec_per_chip_llama2_7b_seq512",
        "value": round(eff_tok_s, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(eff_tok_s / V100_BASELINE_TOK_S, 3),
        "model": model_name,
        "micro_batch_size": bs,
        "raw_tok_s": round(tok_s, 1),
        "step_ms": round(dt * 1000, 1),
        "mfu_percent": round(mfu, 2),
        "flops_normalized": normalized,
        "loss": round(loss, 4),
        "quantize_frozen_base": c.get("quant", ""),
        "remat_policy": c.get("remat_policy", ""),
        "remat_stride": c.get("remat_stride", 0),
        "steps_per_sync": c.get("sync", 1),
        # Goodput attribution over the measured loop (telemetry.ledger):
        # the r06+ BENCH trajectory records where the wall clock went,
        # not just the throughput headline.
        "goodput_fraction": goodput.get("goodput_fraction", 0.0),
        "goodput_buckets": {k: round(v, 4) for k, v in
                            (goodput.get("buckets") or {}).items()},
        # HBM attribution at end of the measured loop
        # (telemetry.memledger): params vs optimizer vs untracked bytes.
        "memory": memory,
        # Watchdog verdict: nonzero means the measured loop misbehaved
        # (hung step etc.) — regression tooling should distrust `value`.
        "watchdog_alerts": (sum(_WATCHDOG.alert_counts().values())
                            if _WATCHDOG is not None else 0),
        "watchdog_alert_rules": (_WATCHDOG.alert_counts()
                                 if _WATCHDOG is not None else {}),
    }
    # Stash for the watchdog (it emits best-so-far if we stall after this
    # point), then print the one official line (_emit is emit-once).
    _BEST["json"] = out
    _emit(out)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException as e:  # the driver contract: ALWAYS one JSON line
        _emit(_error_json(f"{type(e).__name__}: {str(e)[:300]}"))
        raise
