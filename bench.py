"""Headline benchmark: LoRA-SFT training throughput on the local TPU chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "tok/s/chip", "vs_baseline": N, ...}

Baseline: the reference's only recorded training throughput — ZeRO-2,
Llama-2-7B LoRA, micro-bs=1, seq<=512 on one V100-SXM2-32GB at ~2.93 it/s
steady state (BASELINE.md; train.ipynb:442,524,607), i.e. ~1500 tok/s.

We run the same workload (Llama-2-7B + LoRA r=16 on q/k/v/o, seq 512,
AdamW + warmup + clip 1.0, remat) on one TPU chip at the largest micro-batch
that fits, and report achieved tokens/sec/chip. ``vs_baseline`` > 1 means
faster than the reference's V100 number. If the flagship model cannot fit
(e.g. small-HBM dev chip), we fall back to a smaller preset and normalize
the comparison by model FLOPs (reported transparently via ``model`` /
``flops_normalized`` keys).

Env overrides: BENCH_MODEL (preset name), BENCH_BS, BENCH_SEQ, BENCH_STEPS.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp

# Source checkout wins over any installed copy; an installed dlti-tpu
# serves scripts run from outside a checkout.
_repo_root = os.path.dirname(os.path.abspath(__file__))
if os.path.isdir(os.path.join(_repo_root, "dlti_tpu")):
    sys.path.insert(0, _repo_root)
del _repo_root
from dlti_tpu.utils.platform import enable_compilation_cache

enable_compilation_cache()

V100_BASELINE_TOK_S = 2.93 * 512  # ~1500 tok/s (BASELINE.md)
SEQ = int(os.environ.get("BENCH_SEQ", 512))
STEPS = int(os.environ.get("BENCH_STEPS", 10))


def _try_run(model_name: str, micro_bs: int, quant: str = "",
             remat_policy: str = "", remat_stride: int = 0,
             loss_chunk: int = 0, sync: int = 1):
    import dataclasses

    from dlti_tpu.config import MODEL_PRESETS, LoRAConfig, OptimizerConfig
    from dlti_tpu.models import LlamaForCausalLM, count_params
    from dlti_tpu.training import build_optimizer, create_train_state, make_train_step

    if quant not in ("", "int8"):
        raise ValueError(f"unknown BENCH_QUANT={quant!r} (only '' or 'int8')")
    cfg = MODEL_PRESETS[model_name]
    overrides = {}
    if remat_policy == "none":
        overrides["remat"] = False
    elif remat_policy:
        overrides["remat_policy"] = remat_policy
    if remat_stride:
        overrides["remat_stride"] = remat_stride
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    model = LlamaForCausalLM(cfg, LoRAConfig())
    tx = build_optimizer(OptimizerConfig())
    rng = jax.random.PRNGKey(0)
    state = create_train_state(rng, model, tx, (micro_bs, SEQ))
    jax.block_until_ready(jax.tree_util.tree_leaves(state.params)[0])
    trainable, total = count_params(state.params)
    if quant == "int8":
        # Frozen-base weight-only int8 (TrainConfig.quantize_frozen_base):
        # halves base-weight HBM so activation saving fits.
        from dlti_tpu.models.quantization import quantize_params_int8

        state = state.replace(
            params=quantize_params_int8(state.params, donate=True))
        jax.block_until_ready(jax.tree_util.tree_leaves(state.params)[0])

    base_step = make_train_step(model, accum_steps=1, loss_chunk=loss_chunk)
    batch = {
        "input_ids": jax.random.randint(rng, (1, micro_bs, SEQ), 0, cfg.vocab_size),
        "loss_mask": jnp.ones((1, micro_bs, SEQ), jnp.int32),
    }
    # Warmup (compile + 2 calls). NOTE: on the remote-relay PJRT backend in
    # this image, jax.block_until_ready returns before device work finishes,
    # so all timing synchronizes via device_get (a real data dependency) —
    # slightly pessimistic (no host/device pipelining) but honest.
    if sync > 1:
        # Trainer's steps_per_sync path (the same make_multi_step the
        # Trainer scans): `sync` whole optimizer steps per compiled
        # program, one host sync per window — amortizes the fixed
        # per-call dispatch/relay round-trip.
        from dlti_tpu.training import make_multi_step

        step = make_multi_step(base_step)
        batches = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (sync,) + x.shape), batch)

        def run(state, i):
            rngs = jax.vmap(
                lambda j: jax.random.fold_in(rng, i * sync + j)
            )(jnp.arange(sync))
            state, ms = step(state, batches, rngs)
            return state, float(jax.device_get(ms["loss"][-1]))
    else:
        step = jax.jit(base_step, donate_argnums=(0,))

        def run(state, i):
            state, m = step(state, batch, jax.random.fold_in(rng, i))
            return state, float(jax.device_get(m["loss"]))

    # Warmup (indices past the timed range: fold_in rejects negatives).
    state, loss_val = run(state, STEPS)
    state, loss_val = run(state, STEPS + 1)

    t0 = time.perf_counter()
    for i in range(STEPS):
        state, loss_val = run(state, i)
    dt = (time.perf_counter() - t0) / (STEPS * sync)
    tok_s = micro_bs * SEQ / dt
    return tok_s, dt, trainable, total, loss_val


def main() -> None:
    from dlti_tpu.utils.metrics import compute_mfu, detect_chip_peak_flops

    if "BENCH_MODEL" in os.environ:
        quant = os.environ.get("BENCH_QUANT", "")
        if quant not in ("", "int8"):
            # Fail loudly here: the try-loop below treats exceptions as
            # OOMs and would report "no config fit" with exit 0.
            raise SystemExit(f"unknown BENCH_QUANT={quant!r} (only '' or 'int8')")
        candidates = [dict(model=os.environ["BENCH_MODEL"],
                           bs=int(os.environ.get("BENCH_BS", 1)),
                           quant=quant,
                           remat_policy=os.environ.get("BENCH_REMAT", ""),
                           remat_stride=int(os.environ.get("BENCH_STRIDE", 0)),
                           loss_chunk=int(os.environ.get("BENCH_LOSS_CHUNK", 0)),
                           sync=int(os.environ.get("BENCH_SYNC", 1)))]
    else:
        # Ordered by measured throughput on the v5e-class 16 GB chip
        # (results/mfu_investigation_r03.json): int8 frozen base frees
        # ~6.7 GB of base-weight HBM so remat can be disabled entirely
        # (the binding constraint at bf16 —
        # results/mfu_investigation_r02.json), and steps_per_sync scans
        # whole optimizer steps into one compiled call, amortizing the
        # fixed dispatch/relay round-trip. Winner: 65.1% MFU / 4,746
        # tok/s at int8 bs4 no-remat sync=20 (vs 40.8% bf16 in r02).
        candidates = [
            dict(model="llama2_7b", bs=4, quant="int8", remat_policy="none",
                 sync=20),
            dict(model="llama2_7b", bs=4, quant="int8", remat_policy="none",
                 sync=10),
            dict(model="llama2_7b", bs=4, quant="int8", remat_policy="none"),
            dict(model="llama2_7b", bs=4, quant="int8",
                 remat_policy="dots_with_no_batch_dims_saveable"),
            dict(model="llama2_7b", bs=4, quant="int8",
                 remat_policy="dots_saveable"),
            dict(model="llama2_7b", bs=8, quant="int8",
                 remat_policy="save_attn_out", remat_stride=4),
            dict(model="llama2_7b", bs=4, quant="int8"),
            dict(model="llama2_7b", bs=4),
            dict(model="llama2_7b", bs=2),
            dict(model="llama2_7b", bs=1),
            dict(model="llama_1b", bs=8),
        ]

    result = None
    for c in candidates:
        try:
            tok_s, dt, trainable, total, loss = _try_run(
                c["model"], c["bs"], quant=c.get("quant", ""),
                remat_policy=c.get("remat_policy", ""),
                remat_stride=c.get("remat_stride", 0),
                loss_chunk=c.get("loss_chunk", 0),
                sync=c.get("sync", 1))
            result = (c, tok_s, dt, trainable, total, loss)
            break
        except Exception as e:  # OOM or compile failure: try the next config
            print(f"# bench: {c} failed: {type(e).__name__}: "
                  f"{str(e)[:200]}", file=sys.stderr)
            continue
    if result is None:
        print(json.dumps({"metric": "lora_sft_tokens_per_sec_per_chip",
                          "value": 0.0, "unit": "tok/s/chip",
                          "vs_baseline": 0.0, "error": "no config fit"}))
        return

    c, tok_s, dt, trainable, total, loss = result
    model_name, bs = c["model"], c["bs"]
    peak = detect_chip_peak_flops()
    mfu = compute_mfu(tok_s, total, peak, trainable_params=trainable)

    # FLOPs-normalize if we had to fall back below 7B so vs_baseline stays an
    # apples-to-apples compute-rate comparison.
    from dlti_tpu.config import MODEL_PRESETS

    n7b = MODEL_PRESETS["llama2_7b"].num_params()
    normalized = model_name != "llama2_7b"
    eff_tok_s = tok_s * (total / n7b) if normalized else tok_s

    print(json.dumps({
        "metric": "lora_sft_tokens_per_sec_per_chip_llama2_7b_seq512",
        "value": round(eff_tok_s, 1),
        "unit": "tok/s/chip",
        "vs_baseline": round(eff_tok_s / V100_BASELINE_TOK_S, 3),
        "model": model_name,
        "micro_batch_size": bs,
        "raw_tok_s": round(tok_s, 1),
        "step_ms": round(dt * 1000, 1),
        "mfu_percent": round(mfu, 2),
        "flops_normalized": normalized,
        "loss": round(loss, 4),
        "quantize_frozen_base": c.get("quant", ""),
        "remat_policy": c.get("remat_policy", ""),
        "remat_stride": c.get("remat_stride", 0),
        "steps_per_sync": c.get("sync", 1),
    }))


if __name__ == "__main__":
    main()
